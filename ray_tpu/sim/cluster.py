"""Simulated control plane: head, nodes and autoscaler as discrete-event
state machines.

These are the *control* state machines of the real runtime — register/
heartbeat/death declaration (``runtime/health.py``), lease grant and
lost-ack requeue (``runtime/raylet.py``), the breaker→quarantine→
soft-avoid chain (``rpc/breaker.py`` + ``runtime/health.py`` +
scheduler), drain convergence (``cluster_utils.drain_node``), snapshot
persistence and head failover (``runtime/head.py``), lineage
reconstruction (``runtime/recovery.py``) and the autoscaler sizing loop
— re-expressed over the ``Clock``/``Transport`` seams so 10k of them
run in one process.  Where the real modules have a reusable primitive
(``PeerBreaker``, the chaos plane's Philox link streams), the simulator
uses the real class, on virtual time.

Determinism contract: single-threaded, virtual clock, all randomness
from Philox (the chaos instance plus the campaign's own generator), no
iteration over unordered sets.  The same seed replays the same trace,
byte for byte.
"""

from __future__ import annotations

import hashlib
import json
from collections import deque
from dataclasses import dataclass

from ..common.clock import VirtualClock
from ..common.config import get_config
from ..leasing import LeaseGrantor, LocalLeaseCache
from ..rpc.breaker import CLOSED, OPEN, PeerBreaker
from ..rpc.chaos import _Chaos
from ..rpc.client import RpcConnectionError
from .transport import SimTransport

__all__ = ["SimCluster", "SimParams", "SimHead", "SimNode",
           "SimStandby", "SimAutoscaler", "Trace", "ALIVE", "DRAINING",
           "DEAD", "REMOVED"]

ALIVE, DRAINING, DEAD, REMOVED = "alive", "draining", "dead", "removed"
HEAD_ADDR = "sim://head"
STANDBY_ADDR = "sim://standby"

_TRACE_EVENT_CAP = 20000        # stored events; the hash covers ALL

# Modeled head service cost per RPC, in deterministic virtual
# microseconds: the dispatch-throughput denominator.  A scheduling RPC
# (grant, submit, done, spillback) runs the placement machinery +
# serialization; an origin-routed batch forward is a route-table lookup
# plus a send — no placement solve; a heartbeat (and a leased-row TTL
# refresh) is a row-timestamp touch, two orders cheaper; batched ops
# pay a small marginal per item.  The ratio of lease-plane to head-only
# throughput is a pure function of these constants and the RPC counts —
# replay-stable by construction.
_HEAD_RPC_US = 100.0            # full scheduling-path RPC
_HEAD_ROUTE_US = 20.0           # origin-routed forward (no placement)
_HEAD_TOUCH_US = 1.0            # heartbeat / lease-row liveness touch
_HEAD_ITEM_US = 5.0             # marginal cost per batched item


def _row_reserved(cluster, nid: str) -> bool:
    """A row held by an overlay plane (serve replica, loaned-out row,
    or a training-gang member) is off the batch market: the scheduler
    never places on it, the lease plane prices it at zero headroom and
    the autoscaler neither counts it as slack nor idle-drains it."""
    plane = cluster.serve_plane
    if plane is not None and nid in plane.reserved:
        return True
    tplane = cluster.train_plane
    return tplane is not None and nid in tplane.reserved


class Trace:
    """Append-only campaign trace with an incremental sha256 over the
    canonical JSON of every event — the replay fingerprint.  Storage is
    capped (artifacts stay small at 10k nodes); the hash is not.

    ``cov`` is an optional coverage sink (``sim/hunt.py``'s
    ``RunCoverage``): when attached it sees every event — including the
    ones past the storage cap — but never feeds the hash, so attaching
    it cannot perturb replay fingerprints."""

    def __init__(self):
        self.events: list[dict] = []
        self.total = 0
        self._h = hashlib.sha256()
        self.cov = None

    def rec(self, t: float, kind: str, **fields) -> None:
        ev = {"t": round(t, 6), "kind": kind}
        ev.update(fields)
        self._h.update(json.dumps(
            ev, sort_keys=True, separators=(",", ":")).encode())
        self._h.update(b"\n")
        self.total += 1
        if len(self.events) < _TRACE_EVENT_CAP:
            self.events.append(ev)
        if self.cov is not None:
            self.cov.note(ev)

    def hash(self) -> str:
        return self._h.hexdigest()


@dataclass
class SimParams:
    """Timing/shape knobs, defaulted from the ``sim_*`` config knobs."""

    heartbeat_period_s: float = 5.0
    miss_threshold: int = 3
    lease_timeout_s: float = 20.0
    drain_deadline_s: float = 45.0
    node_capacity: int = 4
    boot_delay_s: float = 3.0
    autoscaler_interval_s: float = 5.0
    autoscaler_idle_timeout_s: float = 60.0
    # lease plane + hot standby (r15); both default off so pre-r15
    # campaign trace hashes replay unchanged
    lease_plane: bool = False
    lease_overcommit: float = 2.0
    lease_max_classes: int = 64
    # floor on the per-class budget the sim head carves (the
    # lease_budget_min knob's sim twin); 0 = no floor, the pre-r17
    # capacity x overcommit sizing — the dataclass default keeps
    # directly-constructed campaigns (dispatch_bench, hunts) replaying
    # their recorded trace hashes unchanged
    lease_budget_min: int = 0
    standby: bool = False
    standby_quorum: float = 0.34
    # planted canary bug (r16, default off): the hunt's CI smoke and
    # bench arm it to prove the adversarial search can find and
    # minimize a real injected defect — with it on, a node death while
    # ANY partition is active "loses" the dead node's running tasks
    # (they are never requeued), so the strict final check fires
    canary: bool = False

    @classmethod
    def from_config(cls) -> "SimParams":
        cfg = get_config()
        return cls(
            heartbeat_period_s=cfg.sim_heartbeat_period_s,
            miss_threshold=cfg.sim_heartbeat_miss_threshold,
            lease_timeout_s=cfg.sim_lease_timeout_s,
            drain_deadline_s=cfg.sim_drain_deadline_s,
            node_capacity=cfg.sim_node_capacity,
            boot_delay_s=cfg.sim_boot_delay_s,
            lease_plane=cfg.sim_lease_plane,
            lease_overcommit=cfg.lease_overcommit,
            lease_max_classes=cfg.lease_max_classes,
            lease_budget_min=cfg.lease_budget_min,
            standby=cfg.sim_standby,
            standby_quorum=cfg.standby_quorum,
        )

    @property
    def fence_horizon_s(self) -> float:
        """How long a node may go without confirmed head contact before
        it stops granting locally — the same horizon after which the
        head declares it dead, so self-fencing always precedes a
        death-driven revocation."""
        return self.heartbeat_period_s * self.miss_threshold


class SimNode:
    """One simulated node agent: heartbeat loop, lease execution with
    idempotent re-grant handling, ack retry, drain participation — and,
    with the lease plane on, a :class:`LocalLeaseCache` that admits
    batched submissions locally, spills misses back to the head, and
    self-fences when head contact is lost."""

    def __init__(self, cluster: "SimCluster", nid: str):
        self.cluster = cluster
        self.nid = nid
        self.address = f"sim://{nid}"
        self.clock = cluster.clock
        self.params = cluster.params
        self.alive = True
        self.registered = False
        self.draining = False
        self.running: dict[str, float] = {}     # tid -> started (virtual)
        self.classes: dict[str, str] = {}       # tid -> class (lease path)
        self.local_queue: deque = deque()       # (tid, duration, class)
        self.done: dict[str, str] = {}          # tid -> oid (ack cache)
        self.done_buffer: list = []             # (tid, oid) awaiting flush
        self.holds: dict[str, bool] = {}        # oid -> True
        self.lease: LocalLeaseCache | None = None
        if self.params.lease_plane:
            self.lease = LocalLeaseCache(
                capacity=self.params.node_capacity,
                fence_after_s=self.params.fence_horizon_s,
                overcommit=self.params.lease_overcommit,
                max_classes=self.params.lease_max_classes)
        handlers = {"exec": self._h_exec, "drain": self._h_drain,
                    "ping": self._h_ping}
        if self.params.lease_plane:
            handlers["submit_batch"] = self._h_submit_batch
        self.server = cluster.transport.serve(
            handlers, host=self.address).start()
        self.head = cluster.transport.connect(HEAD_ADDR,
                                              _sim_src=self.address)
        self._standby = None

    def start(self, stagger: float = 0.0) -> None:
        self.clock.call_later(stagger, self._beat)

    # -- heartbeat / (re-)register loop --------------------------------------
    def _beat(self) -> None:
        if not self.alive:
            return
        try:
            if not self.registered:
                reply = self.head.call("register", self.nid,
                                       self.address, self._report())
                self.registered = True
                self._fold_head_reply(reply)
            else:
                payload = self._hb_payload()
                reply = self.head.call("heartbeat", self.nid, payload)
                if reply == "reregister" or (
                        isinstance(reply, dict) and
                        reply.get("op") == "reregister"):
                    # restarted head lost our row: rejoin with state
                    self.registered = False
                    reply = self.head.call("register", self.nid,
                                           self.address, self._report())
                    self.registered = True
                self._fold_head_reply(reply)
                if payload is not None:
                    # the head folded the piggybacked done acks
                    del self.done_buffer[:len(payload["done"])]
        except RpcConnectionError:
            self._vote()        # head down/partitioned: keep beating
        self.clock.call_later(self.params.heartbeat_period_s, self._beat)

    def _report(self) -> dict:
        report = {"running": list(self.running), "done": dict(self.done),
                  "holds": list(self.holds), "draining": self.draining}
        if self.lease is not None:
            # promotion rejoin: the promoted head re-adopts our leases
            # (grant authority stayed here) and our locally-queued work
            report["lease_epoch"] = self.lease.epoch
            report["lease_classes"] = self.lease.held_classes()
            report["leased_queued"] = [tid for tid, _d, _c
                                       in self.local_queue]
        return report

    def _hb_payload(self) -> dict | None:
        if self.lease is None:
            return None
        return {"done": list(self.done_buffer),
                "leased": list(self.running) +
                [tid for tid, _d, _c in self.local_queue]}

    def _fold_head_reply(self, reply) -> None:
        """Confirmed head contact: refresh the fence clock and fold the
        lease epoch + any fresh grants the head piggybacked."""
        if self.lease is None:
            return
        now = self.clock.monotonic()
        self.lease.on_head_contact(now)
        if not isinstance(reply, dict):
            return
        if self.lease.observe_epoch(reply.get("epoch", 0)):
            self._discard_queue("epoch_revoked")
        grants = reply.get("grants")
        if grants:
            self.lease.install(grants, reply.get("epoch", 0))

    def _discard_queue(self, reason: str) -> None:
        """The head revoked our epoch: it already requeued everything
        we had locally admitted but not started — drop it, never start
        a task under a dead epoch."""
        if self.local_queue:
            self.cluster.trace.rec(
                self.clock.monotonic(), "lease_queue_discard",
                node=self.nid, dropped=len(self.local_queue),
                reason=reason)
            self.local_queue.clear()

    def _vote(self) -> None:
        """Head unreachable: vote for standby promotion (quorum is the
        standby's promotion gate; a partitioned minority never wins)."""
        if not self.params.standby:
            return
        if self._standby is None:
            self._standby = self.cluster.transport.connect(
                STANDBY_ADDR, _sim_src=self.address)
        try:
            self._standby.call("vote", self.nid)
        except RpcConnectionError:
            pass

    # -- handlers ------------------------------------------------------------
    def _h_ping(self) -> str:
        return "pong"

    def _h_exec(self, tid: str, duration: float):
        if tid in self.done:
            # late re-grant of finished work: answer from the ack cache
            return {"op": "done", "oid": self.done[tid]}
        if tid in self.running:
            return {"op": "running"}        # dup delivery: idempotent
        if self.draining:
            return {"op": "rejected"}
        self._start(tid, duration, epoch=-1)
        return {"op": "accepted"}

    def _h_submit_batch(self, tasks: list, epoch: int, grants: dict):
        """One framed multi-submit from the head's origin routing:
        admit locally against the leased budgets, spill the rest.
        ``tasks`` is ``[(tid, duration, class_key), ...]``."""
        lease = self.lease
        now = self.clock.monotonic()
        if self.lease.observe_epoch(epoch):
            self._discard_queue("epoch_revoked")
        lease.install(grants, epoch)
        lease.on_head_contact(now)      # the head just reached us
        accepted, spilled = [], []
        for tid, duration, class_key in tasks:
            if tid in self.done:
                accepted.append(tid)    # idempotent re-submit
                continue
            if tid in self.running or any(t == tid for t, _d, _c
                                          in self.local_queue):
                accepted.append(tid)
                continue
            if self.draining or not lease.try_grant(class_key, now):
                spilled.append(tid)
                continue
            self.classes[tid] = class_key
            self.local_queue.append((tid, duration, class_key))
            accepted.append(tid)
        self.cluster.leasing["local_grants"] += len(accepted)
        self.cluster.leasing["spillbacks"] += len(spilled)
        self._pump_local()
        return {"accepted": accepted, "spilled": spilled}

    def _pump_local(self) -> None:
        """Start locally-admitted tasks while run slots are free — but
        never while fenced (head contact lost past the horizon: our
        epoch may already be revoked)."""
        now = self.clock.monotonic()
        while self.local_queue and \
                len(self.running) < self.params.node_capacity:
            if self.lease.fenced(now):
                return      # resume after the next confirmed contact
            tid, duration, _class_key = self.local_queue.popleft()
            self._start(tid, duration, epoch=self.lease.epoch)

    def _start(self, tid: str, duration: float, epoch: int) -> None:
        self.running[tid] = self.clock.monotonic()
        if self.params.lease_plane:
            # the no-double-execution invariant audits this log
            self.cluster.exec_log.append(
                (tid, self.nid, epoch, self.clock.monotonic()))
        self.clock.call_later(duration, lambda: self._complete(tid))

    def _h_drain(self) -> str:
        self.draining = True
        self._discard_queue("drain")
        if not self.running:
            self._drain_done(0)
        return "ok"

    # -- completion / ack ----------------------------------------------------
    def _complete(self, tid: str) -> None:
        if not self.alive or tid not in self.running:
            return
        del self.running[tid]
        oid = "o:" + tid
        self.done[tid] = oid
        if len(self.done) > 512:            # bounded idempotency window
            self.done.pop(next(iter(self.done)))
        self.holds[oid] = True
        class_key = self.classes.pop(tid, None)
        if class_key is not None and self.lease is not None:
            self.lease.release(class_key)
        if self.lease is not None:
            # batched ack: piggybacks on the next heartbeat, with an
            # early flush so a hot node's tail never waits a period
            self.done_buffer.append((tid, oid))
            if len(self.done_buffer) >= 32:
                self._flush_done()
            self._pump_local()
        else:
            self._ack(tid, oid, 0)
        if self.draining and not self.running and not self.local_queue:
            self._drain_done(0)

    def _flush_done(self) -> None:
        if not self.done_buffer:
            return
        batch = list(self.done_buffer)
        try:
            self.head.call("task_done_batch", self.nid, batch)
            del self.done_buffer[:len(batch)]
            self.lease.on_head_contact(self.clock.monotonic())
        except RpcConnectionError:
            self._vote()    # heartbeat retry still holds the buffer

    def _ack(self, tid: str, oid: str, attempt: int) -> None:
        if not self.alive:
            return
        try:
            self.head.call("task_done", self.nid, tid, oid)
            if self.lease is not None:
                self.lease.on_head_contact(self.clock.monotonic())
        except RpcConnectionError:
            self._vote()
            self.clock.call_later(min(8.0, 1.0 + attempt),
                                  lambda: self._ack(tid, oid, attempt + 1))

    def _drain_done(self, attempt: int) -> None:
        if not self.alive or not self.draining or self.running:
            return
        if self.lease is not None:
            self._flush_done()      # never strand buffered acks at exit
        try:
            self.head.call("drain_done", self.nid)
        except RpcConnectionError:
            self.clock.call_later(min(8.0, 1.0 + attempt),
                                  lambda: self._drain_done(attempt + 1))
            return
        # drained and acknowledged: this node's process exits
        self.alive = False
        self.cluster.transport.kill(self.address)
        self.cluster.node_stopped(self.nid)


class SimHead:
    """The simulated head: node table, job/lease tables, snapshot-backed
    persistence (survives kill), death declaration, lost-ack lease
    requeue, drain convergence, breaker-driven quarantine with
    soft-avoid scheduling, and lineage reconstruction."""

    def __init__(self, cluster: "SimCluster"):
        self.cluster = cluster
        self.clock = cluster.clock
        self.params = cluster.params
        self.trace = cluster.trace
        self.persist = cluster.persist      # survives head kill
        self.alive = True
        self.nodes: dict[str, dict] = {}
        self._node_order: list[str] = []
        self._rr = 0
        self.jobs: dict[str, dict] = {}
        self.tasks: dict[str, dict] = {}
        self.objects: dict[str, dict] = {}  # oid -> {producer, copies}
        self.pending: deque[str] = deque()
        self.breakers: dict[str, PeerBreaker] = {}
        self._clients: dict[str, object] = {}
        self.grantor: LeaseGrantor | None = None
        if self.params.lease_plane:
            # revocation epochs journal into the persist dict, so the
            # promoted head never re-issues a revoked epoch
            journal = self.persist.setdefault("lease_epochs", {})

            def _journal(node: str, epoch: int) -> None:
                journal[node] = epoch

            # per-class budgets cover the node's full overcommit bound:
            # a single-class wave (the common repeat-class shape) can
            # fill a node without artificial per-class throttling — the
            # raylet's admitted_total cap enforces the real limit
            self.grantor = LeaseGrantor(
                budget_per_class=max(
                    int(self.params.node_capacity *
                        self.params.lease_overcommit),
                    self.params.lease_budget_min),
                max_classes=self.params.lease_max_classes,
                journal=_journal)
        handlers = {
            "register": self._h_register, "heartbeat": self._h_heartbeat,
            "job_submit": self._h_job_submit, "task_done": self._h_task_done,
            "drain_done": self._h_drain_done, "ping": self._h_ping,
            "status": self._h_status}
        if self.params.lease_plane:
            handlers["spillback"] = self._h_spillback
            handlers["task_done_batch"] = self._h_task_done_batch
        self.server = cluster.transport.serve(
            handlers, host=HEAD_ADDR).start()
        self._restore()
        self.clock.call_later(self.params.heartbeat_period_s,
                              self._monitor)

    def _busy(self, us: float, dispatch: bool = True) -> None:
        """Accrue modeled head service time.  ``dispatch=False`` marks
        pure liveness work (heartbeat row touches) — identical in both
        dispatch modes, so the throughput denominator excludes it and
        the comparison measures what the lease plane actually moves."""
        self.cluster.head_busy_us += us
        if dispatch:
            self.cluster.head_dispatch_us += us

    def _note_dispatch(self) -> None:
        """First dispatch after a head kill closes the failover window."""
        cl = self.cluster
        if cl.last_head_kill_t is not None:
            ms = round((self.clock.monotonic() - cl.last_head_kill_t)
                       * 1000.0, 3)
            cl.failover_ms.append(ms)
            cl.last_head_kill_t = None
            self.trace.rec(self.clock.monotonic(),
                           "failover_first_dispatch", ms=ms)

    # -- persistence ---------------------------------------------------------
    def _restore(self) -> None:
        restored = 0
        if self.grantor is not None:
            self.grantor.restore(self.persist.get("lease_epochs", {}))
        for jid, spec in self.persist["jobs"].items():
            tids = list(spec["tasks"])
            self.jobs[jid] = {"tasks": tids, "status": "running"}
            for tid in tids:
                done_oid = self.persist["done"].get(tid)
                t = {"job": jid, "duration": spec["tasks"][tid],
                     "state": "pending", "node": None, "granted_at": 0.0,
                     "attempts": 0, "oid": None}
                if done_oid is not None:
                    t["state"] = "done"
                    t["oid"] = done_oid
                    self.objects.setdefault(
                        done_oid, {"producer": tid, "copies": {}})
                else:
                    self.pending.append(tid)
                self.tasks[tid] = t
            self._refresh_job(jid)
            restored += 1
        if restored:
            self.trace.rec(self.clock.monotonic(), "head_restore",
                           jobs=restored, pending=len(self.pending))

    # -- handlers ------------------------------------------------------------
    def _h_ping(self) -> str:
        return "pong"

    def _h_register(self, nid: str, address: str, report: dict):
        # membership bootstrap, not dispatch: identical in both
        # dispatch modes, so it stays out of the throughput denominator
        self._busy(_HEAD_RPC_US, dispatch=False)
        now = self.clock.monotonic()
        known = nid in self.nodes
        self.nodes[nid] = {
            "address": address, "state": ALIVE, "last_hb": now,
            "suspect": False, "running": {}, "drain_started": None,
            "idle_since": now, "leased": {},
        }
        if not known:
            self._node_order.append(nid)
        row = self.nodes[nid]
        if report.get("draining"):
            row["state"] = DRAINING
            row["drain_started"] = now
        for tid, oid in report.get("done", {}).items():
            self._mark_done(tid, oid, nid)
        for oid in report.get("holds", ()):
            obj = self.objects.get(oid)
            if obj is not None:
                obj["copies"][nid] = True
        for tid in report.get("running", ()):
            t = self.tasks.get(tid)
            if t is not None and t["state"] != "done":
                t["state"] = "running"
                t["node"] = nid
                t["granted_at"] = now
                row["running"][tid] = True
        if self.grantor is not None:
            # promotion rejoin: grant authority stayed at the raylet —
            # re-adopt its lease set when its epoch is still current
            # (the journal survived the kill), else force a discard
            epoch = self.grantor.epoch(nid)
            if report.get("lease_epoch", 0) == epoch:
                for class_key in report.get("lease_classes", ()):
                    self.grantor.grant(nid, class_key)
                for tid in report.get("leased_queued", ()):
                    t = self.tasks.get(tid)
                    if t is not None and t["state"] in ("pending",
                                                       "leased"):
                        if t["state"] == "pending":
                            try:
                                self.pending.remove(tid)
                            except ValueError:
                                pass
                        t["state"] = "leased"
                        t["node"] = nid
                        t["granted_at"] = now
                        row["leased"][tid] = now
            self._schedule()
            epoch, grants = self.grantor.snapshot_for(nid)
            return {"op": "ok", "epoch": epoch, "grants": grants}
        self._schedule()
        return "ok"

    def _h_heartbeat(self, nid: str, payload: dict | None = None):
        self._busy(_HEAD_TOUCH_US, dispatch=False)
        row = self.nodes.get(nid)
        if row is None or row["state"] in (DEAD, REMOVED):
            if self.grantor is not None:
                return {"op": "reregister"}
            return "reregister"
        now = self.clock.monotonic()
        row["last_hb"] = now
        # serve-plane piggyback: the load digest for this node's replica
        # folds on the heartbeat that carries its liveness — the same
        # no-extra-RPC contract as the live gossip board
        plane = self.cluster.serve_plane
        if plane is not None:
            plane.on_heartbeat(nid)
        if self.grantor is None:
            return "ok"
        if payload is not None:
            self._busy(_HEAD_ITEM_US * len(payload.get("done", ())))
            self._busy(_HEAD_TOUCH_US * len(payload.get("leased", ())),
                       dispatch=False)
            for tid, oid in payload.get("done", ()):
                self._mark_done(tid, oid, nid)
            # a reported leased task is alive at its raylet: refresh it
            # so the TTL sweep only revokes genuinely quiet grants
            for tid in payload.get("leased", ()):
                if tid in row["leased"]:
                    row["leased"][tid] = now
            if payload.get("done"):
                self._schedule()
        return {"op": "ok", "epoch": self.grantor.epoch(nid),
                "grants": None}

    def _class_key(self, duration: float) -> str:
        """Scheduling class of a simulated task.  Durations stand in
        for the interned resource-request vector: tasks of one class
        are shaped alike, which is exactly what makes repeat
        submissions lease-servable."""
        return f"c{duration:g}"

    def _h_job_submit(self, jid: str, tasks: dict) -> str:
        self._busy(_HEAD_RPC_US + _HEAD_ITEM_US * len(tasks))
        if jid not in self.persist["jobs"]:
            # persist BEFORE acking: an acked job survives a head kill
            self.persist["jobs"][jid] = {"tasks": dict(tasks)}
            self.jobs[jid] = {"tasks": list(tasks), "status": "running"}
            for tid, duration in tasks.items():
                self.tasks[tid] = {
                    "job": jid, "duration": duration, "state": "pending",
                    "node": None, "granted_at": 0.0, "attempts": 0,
                    "oid": None}
                self.pending.append(tid)
            self.trace.rec(self.clock.monotonic(), "job_submit", job=jid,
                           tasks=len(tasks))
        self._schedule()
        return "ack"

    def _h_task_done(self, nid: str, tid: str, oid: str) -> str:
        self._busy(_HEAD_RPC_US)
        self._mark_done(tid, oid, nid)
        self._schedule()
        return "ok"

    def _h_task_done_batch(self, nid: str, items: list) -> str:
        self._busy(_HEAD_RPC_US + _HEAD_ITEM_US * len(items))
        for tid, oid in items:
            self._mark_done(tid, oid, nid)
        self._schedule()
        return "ok"

    def _h_spillback(self, nid: str, tids: list) -> str:
        """A raylet handed leased tasks back (budget exhausted, fenced,
        or stale epoch): the head reschedules them globally."""
        self._busy(_HEAD_RPC_US + _HEAD_ITEM_US * len(tids))
        self._repend(nid, tids)
        self._schedule()
        return "ok"

    def _repend(self, nid: str, tids) -> int:
        row = self.nodes.get(nid)
        n = 0
        for tid in tids:
            t = self.tasks.get(tid)
            if t is None or t["state"] not in ("leased", "running"):
                continue
            t["state"] = "pending"
            t["node"] = None
            self.pending.append(tid)
            n += 1
            if row is not None:
                row["leased"].pop(tid, None)
                row["running"].pop(tid, None)
        return n

    def _h_drain_done(self, nid: str) -> str:
        row = self.nodes.get(nid)
        if row is not None and row["state"] == DRAINING:
            self._remove_node(nid, "drained")
        return "ok"

    def _h_status(self) -> dict:
        states: dict[str, int] = {}
        for nid in self._node_order:
            row = self.nodes.get(nid)
            if row is not None:
                states[row["state"]] = states.get(row["state"], 0) + 1
        return {"nodes": states, "jobs": len(self.jobs),
                "pending": len(self.pending)}

    # -- bookkeeping ---------------------------------------------------------
    def _mark_done(self, tid: str, oid: str, nid: str) -> None:
        t = self.tasks.get(tid)
        if t is None:
            return
        # fence late acks from nodes the head already wrote off: the
        # task was requeued when the node was declared dead/removed,
        # and registering a copy on such a row would plant a phantom
        # replica (the gray-window twin of the drain-path leak the r16
        # hunt found).  The retry completes the task with a live copy.
        nrow = self.nodes.get(nid)
        if nrow is None or nrow["state"] in (DEAD, REMOVED):
            return
        prev = t["node"]
        if prev is not None:
            prow = self.nodes.get(prev)
            if prow is not None:
                prow["running"].pop(tid, None)
                prow["leased"].pop(tid, None)
                if not prow["running"]:
                    prow["idle_since"] = self.clock.monotonic()
        nrow["running"].pop(tid, None)
        nrow["leased"].pop(tid, None)
        if not nrow["running"]:
            nrow["idle_since"] = self.clock.monotonic()
        obj = self.objects.setdefault(oid,
                                      {"producer": tid, "copies": {}})
        obj["copies"][nid] = True
        if t["state"] != "done":
            t["state"] = "done"
            t["node"] = None
            t["oid"] = oid
            self.persist["done"][tid] = oid
            self._refresh_job(t["job"])

    def _refresh_job(self, jid: str) -> None:
        job = self.jobs.get(jid)
        if job is None or job["status"] == "succeeded":
            return
        if all(self.tasks[tid]["state"] == "done"
               for tid in job["tasks"]):
            job["status"] = "succeeded"
            self.trace.rec(self.clock.monotonic(), "job_complete",
                           job=jid)

    def _breaker(self, addr: str) -> PeerBreaker:
        b = self.breakers.get(addr)
        if b is None:
            cfg = get_config()
            b = self.breakers[addr] = PeerBreaker(
                addr, cfg.rpc_breaker_failure_threshold,
                cfg.rpc_breaker_reset_s)
        return b

    def _client(self, nid: str):
        c = self._clients.get(nid)
        if c is None:
            c = self._clients[nid] = self.cluster.transport.connect(
                self.nodes[nid]["address"], _sim_src=HEAD_ADDR)
        return c

    def _after_breaker(self, nid: str, b: PeerBreaker) -> None:
        """The quarantine chain: OPEN breaker -> suspect (scheduler
        soft-avoids), CLOSED again -> unquarantined."""
        row = self.nodes.get(nid)
        if row is None:
            return
        if b.state == OPEN and not row["suspect"]:
            row["suspect"] = True
            self.trace.rec(self.clock.monotonic(), "quarantine",
                           node=nid, opens=b.opens)
        elif b.state == CLOSED and row["suspect"]:
            row["suspect"] = False
            self.trace.rec(self.clock.monotonic(), "unquarantine",
                           node=nid)

    # -- scheduling ----------------------------------------------------------
    def _pick_node(self) -> str | None:
        for allow_suspect in (False, True):     # soft-avoid: two passes
            n = len(self._node_order)
            for off in range(n):
                nid = self._node_order[(self._rr + off) % n]
                row = self.nodes.get(nid)
                if row is None or row["state"] != ALIVE:
                    continue
                if _row_reserved(self.cluster, nid):
                    continue    # serve replica, gang member or LOANED
                if row["suspect"] and not allow_suspect:
                    continue
                if len(row["running"]) + len(row["leased"]) >= \
                        self.params.node_capacity:
                    continue
                if row["suspect"] and \
                        not self._breaker(row["address"]).allow():
                    continue        # open breaker: hard fail-fast
                self._rr = (self._rr + off + 1) % n
                return nid
        return None

    def _schedule(self) -> None:
        if not self.alive:
            return
        if self.grantor is not None:
            self._schedule_lease()
            return
        for _ in range(len(self.pending)):
            if not self.pending:
                break
            tid = self.pending.popleft()
            t = self.tasks.get(tid)
            if t is None or t["state"] != "pending":
                continue
            nid = self._pick_node()
            if nid is None:
                self.pending.appendleft(tid)
                break
            self._grant(tid, nid)

    # -- lease-plane dispatch ------------------------------------------------
    def _lease_headroom(self, nid: str) -> int:
        """How many more tasks the head will route at ``nid`` — mirrors
        the raylet's own overcommit admission bound, so routed batches
        rarely spill."""
        row = self.nodes.get(nid)
        if row is None or row["state"] != ALIVE or row["suspect"]:
            return 0
        if _row_reserved(self.cluster, nid):
            return 0
        cap = int(self.params.node_capacity *
                  self.params.lease_overcommit)
        return cap - len(row["running"]) - len(row["leased"])

    def _lease_class_headroom(self, nid: str, class_key: str) -> int:
        """Headroom for one class at one node: the overall overcommit
        bound AND the per-class budget the raylet enforces.  Mirroring
        both means routed batches are admitted, not spilled — the
        head's view of in-flight leases only ever lags toward fewer
        routes, never more."""
        room = self._lease_headroom(nid)
        if room <= 0:
            return 0
        row = self.nodes[nid]
        inflight = 0
        for tid in row["leased"]:
            t = self.tasks.get(tid)
            if t is not None and \
                    self._class_key(t["duration"]) == class_key:
                inflight += 1
        return min(room, self.grantor.budget_per_class - inflight)

    def _schedule_lease(self) -> None:
        """Origin routing: group pending tasks by scheduling class and
        send each group to a node already holding that class's lease
        (one framed multi-submit per origin).  First-of-class falls back
        to global placement and the grant rides the same batch, so the
        admission itself is a local grant at the raylet."""
        by_class: dict[str, list[str]] = {}
        order: list[str] = []
        for _ in range(len(self.pending)):
            tid = self.pending.popleft()
            t = self.tasks.get(tid)
            if t is None or t["state"] != "pending":
                continue
            ck = self._class_key(t["duration"])
            if ck not in by_class:
                by_class[ck] = []
                order.append(ck)
            by_class[ck].append(tid)
        for ck in order:
            tids = by_class[ck]
            while tids:
                origin = self.grantor.origin_for(
                    ck, eligible=lambda nid:
                    self._lease_class_headroom(nid, ck) > 0)
                if origin is None:
                    origin = self._pick_node()
                    if origin is None:
                        # no capacity anywhere: back on the queue
                        for tid in tids:
                            self.pending.append(tid)
                        break
                    self.grantor.grant(origin, ck)
                tids = self._submit_batch(origin, ck, tids)

    def _submit_batch(self, nid: str, class_key: str,
                      tids: list) -> list:
        """One multi-submit to ``nid`` covering its headroom; returns
        the tids still to place (the rest of the class group)."""
        row = self.nodes[nid]
        take = min(len(tids),
                   max(1, self._lease_class_headroom(nid, class_key)))
        batch_tids, rest = tids[:take], tids[take:]
        batch = [(tid, self.tasks[tid]["duration"], class_key)
                 for tid in batch_tids]
        epoch, grants = self.grantor.snapshot_for(nid)
        b = self._breaker(row["address"])
        self._busy(_HEAD_ROUTE_US + _HEAD_ITEM_US * len(batch))
        try:
            reply = self._client(nid).call("submit_batch", batch,
                                           epoch, grants)
        except RpcConnectionError:
            b.record_failure()
            self._after_breaker(nid, b)
            for tid in batch_tids:
                self.pending.append(tid)
            return rest
        b.record_success()
        self._after_breaker(nid, b)
        now = self.clock.monotonic()
        accepted = set(reply.get("accepted", ()))
        for tid in batch_tids:
            t = self.tasks.get(tid)
            if t is None or t["state"] != "pending":
                continue
            if tid in accepted:
                t["state"] = "leased"
                t["node"] = nid
                t["granted_at"] = now
                t["attempts"] += 1
                row["leased"][tid] = now
            else:
                # spillback: the raylet refused (budget, fence, drain);
                # the head stays the single source of truth and will
                # re-route on the next scheduling pass
                self.pending.append(tid)
        if accepted:
            self._note_dispatch()
        return rest

    def _grant(self, tid: str, nid: str) -> None:
        self._busy(_HEAD_RPC_US)
        row = self.nodes[nid]
        b = self._breaker(row["address"])
        t = self.tasks[tid]
        try:
            reply = self._client(nid).call("exec", tid, t["duration"])
        except RpcConnectionError:
            b.record_failure()
            self._after_breaker(nid, b)
            self.pending.append(tid)
            return
        b.record_success()
        self._after_breaker(nid, b)
        if reply.get("op") == "done":
            self._mark_done(tid, reply["oid"], nid)
            return
        if reply.get("op") == "rejected":       # node started draining
            self.pending.append(tid)
            return
        t["state"] = "running"
        t["node"] = nid
        t["granted_at"] = self.clock.monotonic()
        t["attempts"] += 1
        row["running"][tid] = True
        self._note_dispatch()

    # -- drain / death / removal ---------------------------------------------
    def start_drain(self, nid: str, reason: str) -> bool:
        row = self.nodes.get(nid)
        if row is None or row["state"] != ALIVE:
            return False
        row["state"] = DRAINING
        row["drain_started"] = self.clock.monotonic()
        self.trace.rec(self.clock.monotonic(), "drain_start", node=nid,
                       reason=reason)
        try:
            self._client(nid).call("drain")
        except RpcConnectionError:
            pass        # deadline in the monitor will force-remove
        return True

    def _on_node_dead(self, nid: str, reason: str) -> None:
        row = self.nodes[nid]
        row["state"] = DEAD
        requeued = self._requeue_node(nid)
        self._revoke_node(nid, reason)
        self.trace.rec(self.clock.monotonic(), "node_dead", node=nid,
                       reason=reason, requeued=requeued)
        self._remove_node(nid, "dead")

    def _requeue_node(self, nid: str) -> int:
        row = self.nodes[nid]
        requeued = 0
        # canary (params.canary, default off): drop — instead of
        # requeueing — the running set of a node that dies while a
        # partition is live.  The hunt's smoke target: reachable only
        # by composing two fault ops, so a schedule must be FOUND, and
        # minimizable to exactly that pair.
        lose = self.params.canary and bool(self.cluster.chaos.partitions)
        for tid in list(row["running"]):
            t = self.tasks.get(tid)
            if t is not None and t["state"] == "running" and \
                    t["node"] == nid:
                if lose:
                    continue
                t["state"] = "pending"
                t["node"] = None
                self.pending.append(tid)
                requeued += 1
        row["running"].clear()
        for tid in list(row["leased"]):
            t = self.tasks.get(tid)
            if t is not None and t["state"] == "leased" and \
                    t["node"] == nid:
                t["state"] = "pending"
                t["node"] = None
                self.pending.append(tid)
                requeued += 1
        row["leased"].clear()
        return requeued

    def _revoke_node(self, nid: str, reason: str) -> None:
        """Bump the node's lease epoch (journaled) and forget its grant
        set: any grant it stamped below the new epoch is dead."""
        if self.grantor is None:
            return
        epoch = self.grantor.drop_node(nid, reason)
        now = self.clock.monotonic()
        self.cluster.leasing["revocations"] += 1
        self.cluster.revocation_log.setdefault(nid, []).append(
            (epoch, now))
        self.trace.rec(now, "lease_revoked", node=nid, epoch=epoch,
                       reason=reason)

    def _remove_node(self, nid: str, reason: str) -> None:
        row = self.nodes[nid]
        if row["state"] != DEAD:
            self._requeue_node(nid)
            self._revoke_node(nid, reason)
        # a removed node's replicas leave the cluster with it —
        # whether it died or drained cleanly (drain migrates tasks,
        # not objects).  Scrub its copy registrations so lineage
        # repair sees the loss; a phantom copy on a REMOVED row would
        # block reconstruction forever.  Found by the r16 hunt
        # (tests/data/hunt_finding_object_copies_r16.json): the scrub
        # used to run only on the death path, so a clean drain — e.g.
        # the autoscaler removing post-failover surge capacity — leaked
        # its replicas into the registry.
        for oid in list(self.objects):
            self.objects[oid]["copies"].pop(nid, None)
        row["state"] = REMOVED
        row["drain_started"] = None
        self.trace.rec(self.clock.monotonic(), "node_removed", node=nid,
                       reason=reason)

    # -- the periodic monitor ------------------------------------------------
    def _monitor(self) -> None:
        if not self.alive:
            return
        now = self.clock.monotonic()
        p = self.params
        hb_deadline = p.heartbeat_period_s * p.miss_threshold
        for nid in self._node_order:
            row = self.nodes.get(nid)
            if row is None:
                continue
            state = row["state"]
            if state in (ALIVE, DRAINING) and \
                    now - row["last_hb"] > hb_deadline:
                self._on_node_dead(nid, "heartbeat_timeout")
                continue
            if state == DRAINING and row["drain_started"] is not None \
                    and now - row["drain_started"] > p.drain_deadline_s:
                self._remove_node(nid, "drain_deadline")
                continue
            # lost-ack lease recovery
            for tid in list(row["running"]):
                t = self.tasks.get(tid)
                if t is None or t["state"] != "running":
                    row["running"].pop(tid, None)
                    continue
                if now - t["granted_at"] > p.lease_timeout_s:
                    row["running"].pop(tid, None)
                    t["state"] = "pending"
                    t["node"] = None
                    self.pending.append(tid)
                    self.trace.rec(now, "lease_requeued", task=tid,
                                   node=nid)
            # quiet-lease TTL sweep: a grant the raylet stopped
            # reporting went quiet past the TTL — revoke the node's
            # whole epoch (the raylet's queue dies with it) and requeue
            # everything it was leased, so nothing starts twice without
            # the epoch fence on record
            if self.grantor is not None and row["leased"]:
                quiet = any(now - last > p.lease_timeout_s
                            for last in row["leased"].values())
                if quiet:
                    epoch = self.grantor.revoke(nid, "quiet_lease")
                    self.cluster.leasing["revocations"] += 1
                    self.cluster.revocation_log.setdefault(
                        nid, []).append((epoch, now))
                    requeued = self._repend(nid, list(row["leased"]))
                    self.trace.rec(now, "lease_revoked", node=nid,
                                   epoch=epoch, reason="quiet_lease",
                                   requeued=requeued)
            # half-open probes for quarantined nodes
            if row["state"] == ALIVE and row["suspect"]:
                b = self._breaker(row["address"])
                if b.allow():
                    try:
                        self._client(nid).call("ping")
                        b.record_success()
                    except RpcConnectionError:
                        b.record_failure()
                    self._after_breaker(nid, b)
        # lineage: outputs of done tasks that lost every copy while the
        # job still needs them are reconstructed by re-running the task
        for jid, job in self.jobs.items():
            if job["status"] == "succeeded":
                continue
            for tid in job["tasks"]:
                t = self.tasks[tid]
                if t["state"] == "done":
                    obj = self.objects.get(t["oid"])
                    if obj is None or not obj["copies"]:
                        t["state"] = "pending"
                        t["node"] = None
                        self.pending.append(tid)
                        self.trace.rec(now, "reconstruct", task=tid,
                                       job=jid)
        self._schedule()
        self.clock.call_later(p.heartbeat_period_s, self._monitor)


class SimStandby:
    """Hot-standby head.  A follower that tails the shared persist dict
    (job table, done acks and the lease-epoch journal all live there),
    probes the primary at a quarter-heartbeat cadence, and collects
    raylet votes: every node that fails an RPC to the head votes here.

    Promotion is double-gated — the standby must have missed >= 2 of
    its own probes AND hold votes from a quorum fraction of live nodes.
    Under an asymmetric partition that cuts only the standby<->head
    link, the nodes keep reaching the head, never vote, and the lone
    standby can't split-brain; under a real head death both gates open
    within half a heartbeat period and the standby promotes by calling
    ``cluster.start_head()`` — which restores jobs, done acks and the
    revocation-epoch journal, so outstanding leases survive."""

    def __init__(self, cluster: "SimCluster"):
        self.cluster = cluster
        self.clock = cluster.clock
        self.params = cluster.params
        self.alive = True
        self.votes: set[str] = set()        # counted, never iterated
        self.probe_failures = 0
        self.server = cluster.transport.serve(
            {"vote": self._h_vote, "ping": self._h_ping},
            host=STANDBY_ADDR).start()
        self._head = cluster.transport.connect(HEAD_ADDR,
                                               _sim_src=STANDBY_ADDR)
        self.clock.call_later(self._probe_interval, self._probe)

    @property
    def _probe_interval(self) -> float:
        return self.params.heartbeat_period_s / 4.0

    def _h_ping(self) -> str:
        return "pong"

    def _h_vote(self, nid: str) -> str:
        self.votes.add(nid)
        self._maybe_promote()
        return "ok"

    def _probe(self) -> None:
        if not self.alive or not self.cluster.running:
            return
        try:
            self._head.call("ping")
            # primary reachable from here: clear stale votes so a past
            # blip can never combine with a later one into a quorum
            self.probe_failures = 0
            self.votes.clear()
        except RpcConnectionError:
            self.probe_failures += 1
            self._maybe_promote()
        self.clock.call_later(self._probe_interval, self._probe)

    def _maybe_promote(self) -> None:
        if not self.alive or self.probe_failures < 2:
            return
        need = max(1, -(-int(self.params.standby_quorum * 1000 *
                             self.cluster.alive_count) // 1000))
        if len(self.votes) < need:
            return
        self._promote()

    def _promote(self) -> None:
        cl = self.cluster
        if cl.head is not None and cl.head.alive:
            return      # primary is actually alive: never split-brain
        self.alive = False
        cl.transport.kill(STANDBY_ADDR)
        cl.trace.rec(self.clock.monotonic(), "standby_promote",
                     votes=len(self.votes),
                     probe_failures=self.probe_failures)
        cl.promotions += 1
        cl.start_head()     # restores persist incl. the epoch journal
        cl.standby = SimStandby(cl)     # a fresh follower takes over


class SimAutoscaler:
    """Sizing loop over the simulated head's node table: launches to
    cover pending demand and the min floor, drains idle surplus."""

    def __init__(self, cluster: "SimCluster", min_nodes: int,
                 max_nodes: int):
        self.cluster = cluster
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.launched = 0
        self.drained = 0
        cluster.clock.call_later(cluster.params.autoscaler_interval_s,
                                 self._tick)

    def _tick(self) -> None:
        cl = self.cluster
        if not cl.running:
            return
        head = cl.head
        if head is not None and head.alive:
            p = cl.params
            now = cl.clock.monotonic()
            alive = []
            free = 0
            for nid in head._node_order:
                row = head.nodes.get(nid)
                if row is not None and row["state"] == ALIVE:
                    alive.append(nid)
                    if _row_reserved(cl, nid):
                        continue    # serve/gang/LOANED: no batch slack
                    if not row["suspect"]:
                        free += p.node_capacity - len(row["running"])
            pending = len(head.pending)
            up = 0
            if pending > free:
                up = -(-(pending - free) // p.node_capacity)  # ceil
            if len(alive) < self.min_nodes:
                up = max(up, self.min_nodes - len(alive))
            up = max(0, min(up, self.max_nodes - len(alive)))
            if up:
                for _ in range(up):
                    cl.launch_node(booting=True)
                self.launched += up
                cl.trace.rec(now, "scale_up", count=up,
                             pending=pending)
            elif pending == 0 and len(alive) > self.min_nodes:
                surplus = len(alive) - self.min_nodes
                drained = 0
                for nid in alive:
                    if drained >= min(2, surplus):  # gentle: <=2/tick
                        break
                    if _row_reserved(cl, nid):
                        continue    # never idle-drain serve/gang rows
                    row = head.nodes[nid]
                    if not row["running"] and \
                            now - row["idle_since"] > \
                            p.autoscaler_idle_timeout_s:
                        if head.start_drain(nid, "idle_surplus"):
                            drained += 1
                self.drained += drained
        cl.clock.call_later(cl.params.autoscaler_interval_s, self._tick)


class SimCluster:
    """Owns the virtual clock, the sim transport, the chaos instance and
    every simulated component.  ``install()``/``close()`` swap the
    process clock seam in and out (the campaign runner brackets runs
    with them)."""

    def __init__(self, num_nodes: int, seed: int = 0,
                 params: SimParams | None = None,
                 chaos_params: dict | None = None):
        self.seed = int(seed)
        self.clock = VirtualClock()
        self.params = params or SimParams.from_config()
        self.chaos = _Chaos(seed=self.seed, **(chaos_params or {}))
        self.transport = SimTransport(chaos=self.chaos)
        self.trace = Trace()
        self.persist: dict = {"jobs": {}, "done": {}}
        self.nodes: dict[str, SimNode] = {}
        self._next_node = 0
        self.alive_count = 0
        self.peak_nodes = 0
        self.running = True
        self.head: SimHead | None = None
        self.autoscaler: SimAutoscaler | None = None
        self.serve_plane = None     # installed by serve_diurnal campaigns
        self.train_plane = None     # installed by train_diurnal campaigns
        # lease plane + failover bookkeeping (cluster-scoped so it
        # survives head kills; the promoted head keeps accruing)
        self.head_busy_us = 0.0
        self.head_dispatch_us = 0.0     # busy minus liveness touches
        self.leasing = {"local_grants": 0, "spillbacks": 0,
                        "revocations": 0}
        self.exec_log: list = []        # (tid, nid, epoch, start_t)
        self.exec_audited = 0           # starts already invariant-checked
        self.revocation_log: dict[str, list] = {}   # nid -> [(epoch, t)]
        self.failover_ms: list[float] = []
        self.last_head_kill_t: float | None = None
        self.promotions = 0
        self.standby: SimStandby | None = None
        self.start_head()
        if self.params.standby:
            self.standby = SimStandby(self)
        period = self.params.heartbeat_period_s
        for i in range(num_nodes):
            # stagger first beats across one period so 10k registrations
            # don't land on a single timestamp
            self.launch_node(stagger=period * i / max(1, num_nodes))
        self.trace.rec(0.0, "cluster_start", nodes=num_nodes,
                       seed=self.seed)

    # -- clock seam management ----------------------------------------------
    def install(self) -> "SimCluster":
        from ..common import clock as _clk
        self._prev_clock = _clk.get_clock()
        _clk.install(self.clock)
        return self

    def close(self) -> None:
        from ..common import clock as _clk
        self.running = False
        if getattr(self, "_prev_clock", None) is not None:
            _clk.install(self._prev_clock)
            self._prev_clock = None
        else:
            _clk.uninstall()

    def __enter__(self) -> "SimCluster":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- topology ------------------------------------------------------------
    def start_head(self) -> SimHead:
        self.head = SimHead(self)
        return self.head

    def kill_head(self) -> None:
        if self.head is not None:
            self.head.alive = False
            self.transport.kill(HEAD_ADDR)
            self.head = None
            # failover window opens: closed by the first dispatch of
            # whichever head comes back (restart or standby promotion)
            self.last_head_kill_t = self.clock.monotonic()

    def launch_node(self, stagger: float | None = None,
                    booting: bool = False) -> str:
        nid = f"n{self._next_node:05d}"
        self._next_node += 1
        delay = self.params.boot_delay_s if booting else (stagger or 0.0)
        if booting:
            self.clock.call_later(delay, lambda: self._boot(nid, 0.0))
        else:
            self._boot(nid, delay)
        return nid

    def _boot(self, nid: str, stagger: float) -> None:
        if not self.running:
            return
        node = SimNode(self, nid)
        self.nodes[nid] = node
        node.start(stagger=stagger)
        self.alive_count += 1
        self.peak_nodes = max(self.peak_nodes, self.alive_count)

    def kill_node(self, nid: str) -> bool:
        node = self.nodes.get(nid)
        if node is None or not node.alive:
            return False
        node.alive = False
        self.transport.kill(node.address)
        self.alive_count -= 1
        return True

    def node_stopped(self, nid: str) -> None:
        """A node exited cleanly (post-drain)."""
        self.alive_count -= 1

    def enable_autoscaler(self, min_nodes: int,
                          max_nodes: int) -> SimAutoscaler:
        self.autoscaler = SimAutoscaler(self, min_nodes, max_nodes)
        return self.autoscaler

    # -- convenience ---------------------------------------------------------
    def alive_node_ids(self) -> list[str]:
        return [nid for nid, n in self.nodes.items() if n.alive]

    def stats(self) -> dict:
        tr = self.transport
        busy_s = self.head_busy_us / 1e6
        disp_s = self.head_dispatch_us / 1e6
        done = len(self.persist["done"])
        s = {
            "virtual_s": round(self.clock.monotonic(), 3),
            "events_fired": self.clock.fired,
            "rpc_calls": tr.calls,
            "rpc_dropped": tr.dropped,
            "rpc_dup": tr.dup_delivered,
            "rpc_unreachable": tr.unreachable,
            "chaos_partitioned": self.chaos.num_partitioned,
            "chaos_delayed": self.chaos.num_delayed,
            "peak_nodes": self.peak_nodes,
            "trace_events": self.trace.total,
            # dispatch throughput over modeled head service time
            # attributable to dispatching (liveness touches excluded —
            # they are identical in both modes): the lease-vs-head-only
            # comparison the bench records
            "dispatch": {
                "tasks_done": done,
                "head_busy_s": round(busy_s, 6),
                "head_dispatch_s": round(disp_s, 6),
                "throughput_per_s": round(done / disp_s, 3)
                if disp_s else 0.0,
            },
        }
        if self.params.lease_plane:
            hits = self.leasing["local_grants"]
            miss = self.leasing["spillbacks"]
            s["leasing"] = {
                "leases_granted_local": hits,
                "spillbacks": miss,
                "lease_hit_rate": round(hits / (hits + miss), 4)
                if hits + miss else 0.0,
                "lease_revocations": self.leasing["revocations"],
                "lease_starts": self.exec_audited + len(self.exec_log),
                "promotions": self.promotions,
                "failover_ms": [round(ms, 3)
                                for ms in self.failover_ms],
            }
        return s
