"""SimTransport: the in-process ``Transport`` — a call is a function call.

Address space is ``sim://<name>``; a server is a handler table in a
registry dict, a client is a thin handle that resolves the address *at
call time* (so a head restarted at the same address is reachable
through clients minted before the kill, exactly like a reconnecting
socket client).

Fault injection: every request and reply leg takes one decision from
the chaos plane's directed-link Philox stream
(``_Chaos.link_action(src, dst)``).  ``drop``/partition raises
``RpcConnectionError`` at the caller; ``dup`` invokes the handler twice
(at-least-once delivery — handlers must be idempotent, same contract as
the socket path); a drawn delay advances the *virtual* clock.  A
dropped **reply** still executes the handler — the gray failure where
work happened but the caller can't know.

Single-threaded by design: the simulator owns the event loop, so no
locks, no reader threads, no buffers — which is what makes 10k nodes'
control traffic fit in one process.
"""

from __future__ import annotations

from ..rpc.client import RemoteRpcError, RpcConnectionError
from ..rpc.transport import Transport

__all__ = ["SimTransport", "SimClient", "SimServer", "SimFuture"]


class SimFuture:
    """Parity shim for ``RpcClient.call_async``: the call already
    happened synchronously; this just holds the outcome."""

    __slots__ = ("_value", "_error")

    def __init__(self, value=None, error=None):
        self._value = value
        self._error = error

    def result(self, timeout=None):
        if self._error is not None:
            raise self._error
        return self._value

    def done(self) -> bool:
        return True


class SimServer:
    """Handler table + accounting, mirroring the ``RpcServer`` surface
    the control plane uses (``start/stop/address/add_handler/
    on_conn_close/method_calls/method_bytes``)."""

    def __init__(self, transport: "SimTransport", handlers: dict,
                 address: str):
        self._transport = transport
        self.handlers = dict(handlers)
        self._address = address
        self.alive = False
        self.method_calls: dict[str, int] = {}
        self.method_bytes: dict[str, int] = {}
        self._conn_close_cbs: list = []

    @property
    def address(self) -> str:
        return self._address

    def start(self) -> "SimServer":
        self._transport._bind(self)
        self.alive = True
        return self

    def add_handler(self, name: str, fn) -> None:
        self.handlers[name] = fn

    def on_conn_close(self, cb) -> None:
        self._conn_close_cbs.append(cb)

    def stop(self) -> None:
        self.alive = False
        self._transport._unbind(self)


class SimClient:
    """Parity shim for the ``RpcClient`` surface: ``call``,
    ``call_async``, ``close``, ``peer_address``.  ``src`` names the
    calling endpoint for chaos link identity (``src->dst``)."""

    def __init__(self, transport: "SimTransport", address: str,
                 src: str = "driver", timeout: float | None = None,
                 on_close=None, **_ignored):
        self._transport = transport
        self.peer_address = address
        self.src = src
        self._closed = False
        self._on_close = on_close

    def call(self, method: str, *args, timeout=None, **kwargs):
        if self._closed:
            raise RpcConnectionError("sim client closed")
        return self._transport.deliver(self.src, self.peer_address,
                                       method, args, kwargs)

    def call_async(self, method: str, *args, on_done=None, **kwargs):
        try:
            value = self.call(method, *args, **kwargs)
            fut = SimFuture(value=value)
        except Exception as e:        # noqa: BLE001 — future carries it
            fut = SimFuture(error=e)
        if on_done is not None:
            on_done(fut)
        return fut

    def close(self) -> None:
        self._closed = True


class SimTransport(Transport):
    """In-process registry transport.  ``chaos`` is a private
    ``rpc.chaos._Chaos`` instance (NOT the process-global one) so a
    campaign's streams never collide with real-cluster chaos state."""

    scheme = "sim"

    def __init__(self, chaos=None):
        self._servers: dict[str, SimServer] = {}
        self.chaos = chaos
        self._auto = 0
        # accounting (bench + trace summaries)
        self.calls = 0
        self.dropped = 0
        self.dup_delivered = 0
        self.unreachable = 0

    # -- Transport interface -------------------------------------------------
    def connect(self, address: str, **kwargs) -> SimClient:
        src = kwargs.pop("_sim_src", "driver")
        return SimClient(self, address, src=src, **kwargs)

    def serve(self, handlers: dict, host: str = "sim", port: int = 0
              ) -> SimServer:
        if host.startswith("sim://"):
            address = host
        else:
            self._auto += 1
            name = host if host not in ("sim", "127.0.0.1") else \
                f"ep{self._auto}"
            address = f"sim://{name}"
        return SimServer(self, handlers, address)

    # -- registry ------------------------------------------------------------
    def _bind(self, server: SimServer) -> None:
        live = self._servers.get(server.address)
        if live is not None and live.alive and live is not server:
            raise RuntimeError(f"sim address in use: {server.address}")
        self._servers[server.address] = server

    def _unbind(self, server: SimServer) -> None:
        if self._servers.get(server.address) is server:
            del self._servers[server.address]

    def kill(self, address: str) -> bool:
        """SIGKILL analogue: the endpoint vanishes mid-flight (no
        goodbye, no conn-close callbacks fire at peers)."""
        srv = self._servers.pop(address, None)
        if srv is not None:
            srv.alive = False
            return True
        return False

    # -- the wire ------------------------------------------------------------
    def deliver(self, src: str, dst: str, method: str, args, kwargs):
        self.calls += 1
        ch = self.chaos
        act = ch.link_action(src, dst) if ch is not None else None
        if act == "drop":
            self.dropped += 1
            raise RpcConnectionError(
                f"sim: request {src}->{dst}:{method} dropped")
        srv = self._servers.get(dst)
        if srv is None or not srv.alive:
            self.unreachable += 1
            raise RpcConnectionError(f"sim: {dst} is down")
        fn = srv.handlers.get(method)
        if fn is None:
            raise RemoteRpcError("KeyError",
                                 f"no handler {method!r} at {dst}", "")
        srv.method_calls[method] = srv.method_calls.get(method, 0) + 1
        if act == "dup":
            self.dup_delivered += 1
            fn(*args, **kwargs)     # first delivery; reply discarded
        result = fn(*args, **kwargs)
        # reply leg: the handler RAN either way
        ract = ch.link_action(dst, src) if ch is not None else None
        if ract == "drop":
            self.dropped += 1
            raise RpcConnectionError(
                f"sim: reply {dst}->{src}:{method} dropped")
        return result
