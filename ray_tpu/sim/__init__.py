"""In-process cluster simulator: the control plane at 10k nodes.

SCALING_r05 measured the host, not the architecture — real agents
contend for 1.5 cores long before the drain/autoscaler/recovery
machinery is stressed.  This package runs the control-plane state
machines (head, node, autoscaler) single-process behind the two seams
the rest of the tree now honors:

- ``common/clock.py`` — a ``VirtualClock`` advances event-by-event, so
  heartbeat periods, lease timeouts, breaker cooldowns and drain
  deadlines are exact virtual quantities with no wall-clock sleeps;
- ``rpc/transport.py`` — ``SimTransport`` resolves ``sim://`` addresses
  to in-process handler tables, with every message routed through the
  chaos plane's per-link Philox streams (``_Chaos.link_action``), so a
  campaign's drop/dup/delay/partition schedule replays bit-for-bit from
  its seed.

``campaign.py`` scripts the failure campaigns (rolling kills, asymmetric
partitions, gray-slow links, drain-under-churn, autoscaler flapping),
checks invariants after every injected event, and emits a replayable
trace artifact keyed by seed (``ray_tpu simulate``).

``hunt.py`` + ``minimize.py`` turn the same determinism into a search
engine (``ray_tpu hunt``): fault schedules become serializable genomes,
a seeded mutator explores them guided by trace-derived coverage, and
every invariant violation is ddmin-minimized to a 1-minimal replayable
finding artifact.
"""

from .campaign import CAMPAIGNS, CampaignResult, run_campaign
from .cluster import SimCluster, SimParams
from .hunt import Genome, HuntResult, hunt
from .transport import SimTransport

__all__ = ["SimTransport", "SimCluster", "SimParams", "run_campaign",
           "CAMPAIGNS", "CampaignResult", "Genome", "HuntResult",
           "hunt"]
