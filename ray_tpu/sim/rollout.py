"""Simulated model-version plane: rolling weight hot-swaps under chaos.

The live plane (``ray_tpu/versioning/``) journals a
STAGING -> BROADCASTING -> FLIPPING -> SEALED | ROLLED_BACK state
machine through the KV and flips real replica actors.  The simulator
models the SAME state machine as discrete events on the virtual clock,
layered on :class:`~ray_tpu.sim.serve.SimServePlane`:

* **BROADCASTING** rides a real :class:`SimBroadcastWave` over the
  replica nodes (appended to ``cluster.broadcast_waves``, so the
  campaign kill loops and the broadcast invariants cover it): the new
  weights stream 1->N down the bandwidth-derated tree while routers
  keep serving the old version.
* **FLIPPING** takes replicas one-at-a-time, lowest node id first:
  pull the replica out of routing (``route_ok = False``), poll its
  in-flight load to zero (robust to the replica dying mid-drain —
  accepted work re-dispatches exactly as under any death), re-tag it
  to the new version, run the verification probe, re-enter routing.
* **Session pinning.**  While a rollout is active, every arriving
  session is pinned to the then-serving version; dispatch filters
  power-of-two candidates to the pinned version, so no session is
  served by two versions at once (the ``version-mixed-session``
  invariant counts violations structurally: the pin recorded at
  dispatch vs the replica's tag at completion).  A pin whose version
  has no live replica left migrates to the serving version; pins
  expire after ``rollout_session_idle_s`` of silence and are dropped
  wholesale when the rollout reaches a terminal phase.
* **Failure trips.**  Verification-probe failure (campaign-injected
  via ``probe_fail_at``) and SLO regression (delta-histogram p99 since
  the rollout started exceeding ``rollout_slo_factor`` x the
  pre-rollout p99) roll back: every already-flipped replica re-tags to
  the retained old version.  Replica death mid-flip is tolerated — the
  set shrinks, the rollout continues.
* **Graft-on-pull.**  A replica joining mid-rollout (capacity loan
  warming up) adopts the version matching the phase: the new version
  once flipping started, the old one while still broadcasting.

Determinism contract: the plane draws NOTHING from the RNG — every
decision is a function of cluster state and the virtual clock — and it
only exists when a ``serve_rolling_update`` campaign installs it, so
every other campaign's replay hash is untouched.
"""

from __future__ import annotations

from ..common.config import get_config
from ..versioning import phases
from .broadcast import SimBroadcastWave
from .serve import _LAT_EDGES

__all__ = ["SimRolloutPlane"]

_WAVE_POLL_S = 1.0      # broadcast-terminal poll period
_DRAIN_POLL_S = 0.1     # per-flip drain poll period
_FLIP_GAP_S = 0.01      # spacing between consecutive flips


def _q(hist: list[int], q: float) -> float:
    """Bucket-edge quantile over a latency histogram (same read as
    ``SimServePlane._quantile``, usable on delta histograms)."""
    total = sum(hist)
    if not total:
        return 0.0
    target = q * total
    acc = 0
    for k, cnt in enumerate(hist):
        acc += cnt
        if acc >= target:
            return _LAT_EDGES[k] if k < len(_LAT_EDGES) else \
                _LAT_EDGES[-1] * 2
    return _LAT_EDGES[-1] * 2


class SimRolloutPlane:
    """The model-version overlay a ``serve_rolling_update`` campaign
    installs on a :class:`SimCluster` (as ``cluster.rollout_plane``,
    with ``plane.rollout`` pointing back)."""

    def __init__(self, cluster, plane):
        self.cluster = cluster
        self.plane = plane
        plane.rollout = self
        cluster.rollout_plane = self
        cfg = get_config()
        self.idle_s = float(cfg.rollout_session_idle_s)
        self.fanout = int(cfg.rollout_wave_fanout)
        self.slo_factor = float(cfg.rollout_slo_factor)

        self.serving = "v1"
        self.seq = 1
        for rep in plane.replicas.values():
            rep.version = self.serving
        self.rollouts: list[dict] = []
        self.active: dict | None = None
        self.queued: list[tuple[str, int]] = []
        self.session_pins: dict[int, list] = {}   # session -> [ver, t_last]
        self.req_session: dict[int, int] = {}     # rid -> session
        self.req_tag: dict[int, str] = {}         # rid -> pinned ver at dispatch
        self.mixed_served = 0
        self.migrations = 0
        self.grafts = 0

    # -- lifecycle -----------------------------------------------------------
    def start_rollout(self, artifact: str, probe_fail_at: int = -1) -> str:
        """Stage the next version; queues behind an active rollout
        (one per deployment at a time, like the live registry)."""
        if self.active is not None:
            self.queued.append((artifact, probe_fail_at))
            return "queued"
        self.seq += 1
        new = f"v{self.seq}"
        now = self.cluster.clock.monotonic()
        ro = {
            "id": f"r{self.seq}", "artifact": artifact,
            "from": self.serving, "to": new,
            "phase": phases.STAGING, "flipped": 0, "replicas": 0,
            "old_retained": True, "probe_fail_at": int(probe_fail_at),
            "t_start": now, "t_done": None, "error": "",
            "pre_hist": list(self.plane._hist),
            "pre_p99_s": _q(self.plane._hist, 0.99),
            "during_p99_s": 0.0,
        }
        self.rollouts.append(ro)
        self.active = ro
        self.cluster.trace.rec(now, "rollout_start", rid=ro["id"],
                               from_v=ro["from"], to_v=new,
                               artifact=artifact,
                               probe_fail_at=ro["probe_fail_at"])
        self._phase(ro, phases.BROADCASTING)
        members = sorted(self.plane.replicas)
        wave = SimBroadcastWave(self.cluster, f"rollout-{ro['id']}",
                                members, size_mb=256,
                                fanout=self.fanout)
        self.cluster.broadcast_waves.append(wave)
        wave.start()
        ro["wave"] = wave
        self.cluster.clock.call_later(_WAVE_POLL_S,
                                      lambda: self._poll_wave(ro))
        return ro["id"]

    def _phase(self, ro: dict, phase: str) -> None:
        ro["phase"] = phase
        self.cluster.trace.rec(self.cluster.clock.monotonic(),
                               "rollout_phase", rid=ro["id"],
                               phase=phase)

    def _poll_wave(self, ro: dict) -> None:
        if not self.cluster.running or ro is not self.active:
            return
        if not ro["wave"].terminal:
            self.cluster.clock.call_later(_WAVE_POLL_S,
                                          lambda: self._poll_wave(ro))
            return
        # graft-on-pull: members the wave never reached fetch on their
        # first flip, so a degraded broadcast is not a failed rollout
        self._phase(ro, phases.FLIPPING)
        ro["replicas"] = len(self.plane.replicas)
        self._flip_next(ro)

    # -- the flip sequence ---------------------------------------------------
    def _flip_targets(self, ro: dict) -> list[str]:
        out = []
        for nid in sorted(self.plane.replicas):
            rep = self.plane.replicas[nid]
            if not rep.alive or rep.version == ro["to"]:
                continue
            loan = self.plane.loans.get(nid)
            if loan is not None and loan["state"] == "draining":
                continue    # leaving the pool anyway
            out.append(nid)
        return out

    def _flip_next(self, ro: dict) -> None:
        if not self.cluster.running or ro is not self.active:
            return
        # SLO trip: p99 of completions since the rollout started vs the
        # pre-rollout baseline
        delta = [h - p for h, p in zip(self.plane._hist, ro["pre_hist"])]
        if sum(delta) >= 50 and ro["pre_p99_s"] > 0.0:
            during = _q(delta, 0.99)
            if during > self.slo_factor * ro["pre_p99_s"]:
                ro["during_p99_s"] = during
                self._fail(ro, f"slo: p99 {during:.2f}s > "
                               f"{self.slo_factor}x {ro['pre_p99_s']:.2f}s")
                return
        targets = self._flip_targets(ro)
        if not targets:
            self._seal(ro)
            return
        nid = targets[0]
        rep = self.plane.replicas[nid]
        rep.route_ok = False        # out of routing; drain in-flight
        self._drain_poll(ro, nid, rep.epoch)

    def _drain_poll(self, ro: dict, nid: str, epoch: int) -> None:
        if not self.cluster.running or ro is not self.active:
            return
        clock = self.cluster.clock
        rep = self.plane.replicas.get(nid)
        if rep is None or not rep.alive or rep.epoch != epoch:
            # died mid-flip: accepted work already re-dispatched by
            # _replica_dead; the set shrinks, the rollout continues
            self.cluster.trace.rec(clock.monotonic(), "rollout_flip_dead",
                                   rid=ro["id"], node=nid)
            clock.call_later(_FLIP_GAP_S, lambda: self._flip_next(ro))
            return
        if rep.load() > 0:
            # drain is bounded: no new work routes here and the replica
            # finishes what it holds (or dies, caught above)
            clock.call_later(_DRAIN_POLL_S,
                             lambda: self._drain_poll(ro, nid, epoch))
            return
        # drained: reload + verification probe
        flip_idx = ro["flipped"]
        if ro["probe_fail_at"] >= 0 and flip_idx == ro["probe_fail_at"]:
            rep.route_ok = True     # back into routing on the OLD weights
            self.cluster.trace.rec(clock.monotonic(),
                                   "rollout_probe_fail",
                                   rid=ro["id"], node=nid, flip=flip_idx)
            self._fail(ro, f"probe failed on {nid}")
            return
        rep.version = ro["to"]
        rep.route_ok = True
        ro["flipped"] += 1
        self.cluster.trace.rec(clock.monotonic(), "rollout_flip",
                               rid=ro["id"], node=nid, version=ro["to"],
                               flipped=ro["flipped"])
        clock.call_later(_FLIP_GAP_S, lambda: self._flip_next(ro))

    # -- terminal transitions ------------------------------------------------
    def _seal(self, ro: dict) -> None:
        now = self.cluster.clock.monotonic()
        delta = [h - p for h, p in zip(self.plane._hist, ro["pre_hist"])]
        ro["during_p99_s"] = _q(delta, 0.99)
        ro["phase"] = phases.SEALED
        ro["t_done"] = now
        self.serving = ro["to"]
        self.cluster.trace.rec(now, "rollout_sealed", rid=ro["id"],
                               version=ro["to"], flipped=ro["flipped"],
                               seconds=round(now - ro["t_start"], 4))
        self._finish(ro)

    def _fail(self, ro: dict, error: str) -> None:
        """Roll back: re-tag every already-flipped live replica to the
        retained old version (the retained artifact is replica-local
        after the broadcast, so the re-flip needs no second wave)."""
        now = self.cluster.clock.monotonic()
        ro["error"] = error
        rolled = 0
        for nid in sorted(self.plane.replicas):
            rep = self.plane.replicas[nid]
            if rep.alive and rep.version == ro["to"]:
                rep.version = ro["from"]
                rolled += 1
        delta = [h - p for h, p in zip(self.plane._hist, ro["pre_hist"])]
        ro["during_p99_s"] = _q(delta, 0.99)
        ro["phase"] = phases.ROLLED_BACK
        ro["t_done"] = now
        self.cluster.trace.rec(now, "rollout_rolled_back", rid=ro["id"],
                               error=error, reflipped=rolled,
                               seconds=round(now - ro["t_start"], 4))
        self._finish(ro)

    def _finish(self, ro: dict) -> None:
        ro.pop("wave", None)        # waves stay in cluster.broadcast_waves
        ro.pop("pre_hist", None)
        self.session_pins.clear()   # pins only span an active rollout
        self.active = None
        if self.queued:
            artifact, pf = self.queued.pop(0)
            self.cluster.clock.call_later(
                _FLIP_GAP_S,
                lambda: self.start_rollout(artifact, probe_fail_at=pf))

    # -- serve-plane hooks (every one gated on plane.rollout) ----------------
    def _pin_target(self) -> str:
        """The version a NEW session pins to.  Once the flip frontier
        is moving, new sessions ride the new version — otherwise every
        live session funnels onto the shrinking old-version subset and
        the flip tail melts down mid-peak (old sessions keep their old
        pin until they go idle, exactly like live traffic draining off
        a blue/green edge)."""
        ro = self.active
        if ro is not None and ro["phase"] == phases.FLIPPING and \
                ro["flipped"] > 0:
            return ro["to"]
        return self.serving

    def note_arrival(self, rid: int, session: int, now: float) -> None:
        self.req_session[rid] = session
        if self.active is None:
            return
        pin = self.session_pins.get(session)
        if pin is None or now - pin[1] > self.idle_s:
            # new session (or one idle past the pin window, i.e. ended):
            # pin to the frontier version
            self.session_pins[session] = [self._pin_target(), now]
        else:
            pin[1] = now

    def filter_candidates(self, rid: int, live: list) -> list:
        """Restrict dispatch candidates to the session's pinned
        version; migrate the pin when that version has no live replica
        left, or — at a request boundary, so every single request still
        sees exactly one version — when the pinned side has started
        queuing wall-to-wall (every pinned-version replica at
        ``replica_cap``) while the frontier version has headroom.
        Without the saturation valve a long-lived session population
        funnels onto the shrinking old-version subset as the flip
        frontier advances and the tail of the flip melts down mid-peak.
        Pins only ever move FORWARD (old -> frontier), never back.
        Always returns a non-empty subset of ``live``."""
        session = self.req_session.get(rid)
        pin = None if session is None else self.session_pins.get(session)
        if pin is None:
            self.req_tag.pop(rid, None)
            return live
        subset = [r for r in live if r.version == pin[0]]
        cap = self.plane.p.replica_cap
        if subset and min(r.load() for r in subset) >= cap:
            tgt = self._pin_target()
            if tgt != pin[0]:
                ahead = [r for r in live if r.version == tgt]
                if ahead and min(r.load() for r in ahead) < \
                        min(r.load() for r in subset):
                    pin[0] = tgt
                    self.migrations += 1
                    subset = ahead
        if not subset:
            if pin[0] != self.serving:
                pin[0] = self.serving
                self.migrations += 1
                subset = [r for r in live if r.version == pin[0]]
            if not subset:
                # nothing on the serving version either (mass kill):
                # serving the session beats stalling it
                self.req_tag.pop(rid, None)
                return live
        self.req_tag[rid] = pin[0]
        return subset

    def on_complete(self, rid: int, version: str) -> None:
        self.req_session.pop(rid, None)
        expected = self.req_tag.pop(rid, None)
        if expected is not None and version != expected:
            self.mixed_served += 1

    def on_replica_added(self, nid: str) -> None:
        """Graft-on-pull: a replica joining mid-rollout adopts the
        phase-appropriate version (it pulls the staged artifact from
        the nearest sealed peer rather than re-running the wave)."""
        rep = self.plane.replicas.get(nid)
        if rep is None:
            return
        ro = self.active
        if ro is not None and ro["phase"] == phases.FLIPPING:
            rep.version = ro["to"]
            self.grafts += 1
            self.cluster.trace.rec(self.cluster.clock.monotonic(),
                                   "rollout_graft", rid=ro["id"],
                                   node=nid, version=ro["to"])
        else:
            rep.version = self.serving

    # -- invariants ----------------------------------------------------------
    @property
    def all_terminal(self) -> bool:
        return self.active is None and not self.queued and \
            all(ro["phase"] in phases.TERMINAL for ro in self.rollouts)

    def check(self, strict: bool = False, now: float | None = None,
              grace: float = 10.0) -> tuple[list[str], int]:
        """Rollout invariants, called from
        :func:`sim.invariants.check_invariants`."""
        from .invariants import fmt_violation

        violations: list[str] = []
        checks = 0
        if now is None:
            now = self.cluster.clock.monotonic()
        checks += 1
        if self.mixed_served:
            violations.append(fmt_violation(
                "version-mixed-session", now,
                f"{self.mixed_served} requests served off their "
                f"session's pinned version"))
        checks += 1
        for ro in self.rollouts:
            if ro["phase"] not in phases.TERMINAL and \
                    not ro["old_retained"]:
                violations.append(fmt_violation(
                    "old-version-retained", now,
                    f"rollout {ro['id']} dropped old version "
                    f"{ro['from']} before seal"))
        if strict:
            checks += 1
            open_n = sum(1 for ro in self.rollouts
                         if ro["phase"] not in phases.TERMINAL)
            if open_n or self.queued:
                violations.append(fmt_violation(
                    "rollout-terminal", now,
                    f"{open_n} rollouts not SEALED/ROLLED_BACK and "
                    f"{len(self.queued)} still queued after quiesce"))
        return violations, checks

    # -- reporting -----------------------------------------------------------
    def stats(self) -> dict:
        sealed = sum(1 for r in self.rollouts
                     if r["phase"] == phases.SEALED)
        rolled = sum(1 for r in self.rollouts
                     if r["phase"] == phases.ROLLED_BACK)
        return {
            "serving": self.serving,
            "rollouts": len(self.rollouts),
            "sealed": sealed,
            "rolled_back": rolled,
            "mixed_served": self.mixed_served,
            "migrations": self.migrations,
            "grafts": self.grafts,
            "per_rollout": [{
                "id": r["id"], "from": r["from"], "to": r["to"],
                "phase": r["phase"], "flipped": r["flipped"],
                "replicas": r["replicas"], "error": r["error"],
                "pre_p99_s": r["pre_p99_s"],
                "during_p99_s": r["during_p99_s"],
                "seconds": None if r["t_done"] is None else
                round(r["t_done"] - r["t_start"], 4),
            } for r in self.rollouts],
        }
