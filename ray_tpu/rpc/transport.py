"""Transport seam: how control-plane endpoints get made.

Reference parity: upstream's ``GrpcServer``/``ClientCallManager`` are
constructed inline by every daemon, which welds the control logic to
real sockets.  This module is the one place that decides what a
"connection" and a "server" are, so the same head/agent/autoscaler
state machines can run over:

- ``TcpTransport`` (default) — the real threaded socket
  ``RpcClient``/``RpcServer`` pair; production behavior unchanged.
- ``SimTransport`` (``ray_tpu/sim/transport.py``) — an in-process
  registry where a "call" is a function invocation routed through the
  chaos plane's per-link Philox streams and the virtual clock, so 10k
  simulated nodes fit in one process with zero sockets.

Construction sites in ``runtime/head.py``, ``runtime/node_agent.py``
and ``scripts/cli.py`` go through :func:`connect` / :func:`serve`
rather than naming ``RpcClient``/``RpcServer`` directly; the installed
transport is a process-global with the same None-fast-path shape as
``rpc.chaos._active``.
"""

from __future__ import annotations

__all__ = ["Transport", "TcpTransport", "get_transport", "install",
           "uninstall", "connect", "serve"]


class Transport:
    """The seam: anything that can mint client and server endpoints.

    A client must provide ``call/call_async/close/peer_address``; a
    server must provide ``start/stop/address/add_handler/on_conn_close``
    plus the ``method_calls``/``method_bytes`` accounting dicts — i.e.
    the surface of ``RpcClient``/``RpcServer`` that the control plane
    actually uses.
    """

    scheme = "abstract"

    def connect(self, address: str, **kwargs):
        raise NotImplementedError

    def serve(self, handlers: dict, host: str = "127.0.0.1",
              port: int = 0):
        raise NotImplementedError


class TcpTransport(Transport):
    """The real thing: threaded sockets, length-prefixed frames."""

    scheme = "tcp"

    def connect(self, address: str, **kwargs):
        from .client import RpcClient
        return RpcClient(address, **kwargs)

    def serve(self, handlers: dict, host: str = "127.0.0.1",
              port: int = 0):
        from .server import RpcServer
        return RpcServer(handlers, host=host, port=port)


# -- process-global install --------------------------------------------------
_default = TcpTransport()
_active: Transport = _default


def get_transport() -> Transport:
    return _active


def install(transport: Transport) -> Transport:
    global _active
    _active = transport
    return transport


def uninstall() -> None:
    global _active
    _active = _default


def connect(address: str, **kwargs):
    """Mint a client endpoint for ``address`` via the installed
    transport (kwargs are the usual ``RpcClient`` knobs)."""
    return _active.connect(address, **kwargs)


def serve(handlers: dict, host: str = "127.0.0.1", port: int = 0):
    """Mint a (not-yet-started) server endpoint via the installed
    transport."""
    return _active.serve(handlers, host=host, port=port)
