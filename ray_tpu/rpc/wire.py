"""Frame codec shared by the RPC server and client.

Frames are ``4-byte big-endian length + payload`` over a stream socket.
The raw layer (``send_raw_frame``/``recv_raw_frame``) is codec-agnostic
and shared with the cross-language gateway; this module's default codec
is cloudpickle.  Requests are ``(req_id, method, args, kwargs)``; replies
are ``(req_id, ok: bool, payload)`` where a non-ok payload is
``(exc_type_name, message, traceback_str)``.

Data channel: bulk payloads (object-plane chunks) bypass the pickle
codec entirely.  A handler returns a ``RawResult`` and the server emits
a *raw reply frame* instead of a pickled one: the first payload byte is
``RAW_MARKER`` (0x00) — unambiguous because every cloudpickle stream
starts with the pickle PROTO opcode 0x80 — followed by the req_id, a
small pickled meta object, and the payload bytes verbatim.  The payload
is gather-written with ``socket.sendmsg`` straight from the source
buffer (shm arena view / spill-file bytes, no serialize, no concat
copy) and the receiver hands back a ``memoryview`` into the receive
buffer, so bytes land in their final home with exactly one copy on
each side of the wire.
"""

from __future__ import annotations

import socket
import struct

from ..runtime.serialization import deserialize, serialize

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024       # sanity bound, not a protocol limit

# first payload byte of a codec-bypass reply frame; pickled frames start
# with the pickle PROTO opcode (0x80), so 0x00 can never collide
RAW_MARKER = 0x00
# marker, req_id, flags (bit 0 = ok), meta length
_RAW_HDR = struct.Struct(">BQBI")
# the same header with the marker byte already consumed (the reply
# demultiplexer reads one byte to classify the frame)
_RAW_HDR_REST = struct.Struct(">QBI")

# past this size the header is gather-written alongside the payload
# instead of concatenated (the + would copy the payload to prepend a
# few bytes)
_SMALL_FRAME = 1 << 16

# chaos seam: ``rpc/chaos.py`` installs a per-connection bandwidth pacer
# (callable(sock, nbytes)) here when a cap is configured; None (the
# default) keeps the send path at a single global load + is-None test
_chaos_pacer = None


class RawResult:
    """Marker a handler returns to reply over the raw data channel:
    ``meta`` rides as a (small) pickled object, ``payload`` as raw
    bytes with no codec pass.  ``release`` (if set) runs once the bytes
    are on the socket — how the object store's shm pin is held exactly
    as long as the send needs the buffer."""

    __slots__ = ("meta", "payload", "release")

    def __init__(self, meta, payload=b"", release=None):
        self.meta = meta
        self.payload = payload
        self.release = release


class RawReply:
    """Client-side decoded raw reply: ``meta`` (unpickled small object)
    plus a zero-copy ``payload`` memoryview into the receive buffer —
    or ``payload=None`` when the bytes were received straight into a
    caller-provided sink (see ``recv_reply``)."""

    __slots__ = ("meta", "payload")

    def __init__(self, meta, payload):
        self.meta = meta
        self.payload = payload


def sendmsg_all(sock: socket.socket, buffers) -> None:
    """Gather-write every buffer completely.  ``sendmsg`` is one
    syscall for header+payload with no concatenation copy, but may
    write short — loop, advancing past what the kernel took."""
    bufs = [b if isinstance(b, memoryview) else memoryview(b)
            for b in buffers]
    total = sum(b.nbytes for b in bufs)
    sent = 0
    while sent < total:
        n = sock.sendmsg(bufs)
        sent += n
        if sent >= total:
            return
        while bufs and n >= bufs[0].nbytes:
            n -= bufs[0].nbytes
            bufs.pop(0)
        if bufs and n:
            bufs[0] = bufs[0][n:]


def send_raw_frame(sock: socket.socket, data) -> None:
    """``data`` may be bytes, bytearray, or memoryview."""
    n = data.nbytes if isinstance(data, memoryview) else len(data)
    if _chaos_pacer is not None:
        _chaos_pacer(sock, n)
    if n > _SMALL_FRAME:
        # large frame: gather-write header+payload in one syscall,
        # zero-copy from the caller's buffer
        sendmsg_all(sock, [_LEN.pack(n), data])
        return
    sock.sendall(_LEN.pack(n) + bytes(data))


def send_raw_reply(sock: socket.socket, req_id: int, meta_bytes: bytes,
                   payload, ok: bool = True) -> int:
    """One codec-bypass reply frame; returns its wire byte count.
    ``payload`` is any buffer — it is gather-written verbatim."""
    if not isinstance(payload, memoryview):
        payload = memoryview(payload)
    hdr = _RAW_HDR.pack(RAW_MARKER, req_id, 1 if ok else 0,
                        len(meta_bytes))
    n = len(hdr) + len(meta_bytes) + payload.nbytes
    if _chaos_pacer is not None:
        _chaos_pacer(sock, n)
    sendmsg_all(sock, [_LEN.pack(n), hdr, meta_bytes, payload])
    return n


def is_raw_frame(frame) -> bool:
    return len(frame) > 0 and frame[0] == RAW_MARKER


def parse_raw_reply(frame) -> tuple[int, bool, "RawReply"]:
    """(req_id, ok, RawReply) from a raw reply frame's payload buffer.
    The returned payload is a memoryview INTO ``frame`` — valid as long
    as the caller keeps the buffer alive, copied only when it lands in
    its final home."""
    _marker, req_id, flags, meta_len = _RAW_HDR.unpack_from(frame, 0)
    off = _RAW_HDR.size
    meta = (deserialize(bytes(frame[off:off + meta_len]))
            if meta_len else None)
    view = frame if isinstance(frame, memoryview) else memoryview(frame)
    return req_id, bool(flags & 1), RawReply(meta, view[off + meta_len:])


def recv_raw_frame(sock: socket.socket) -> bytes | None:
    """One frame's payload bytes, or None on clean EOF."""
    buf = recv_raw_frame_buf(sock)
    return None if buf is None else bytes(buf)


def recv_raw_frame_buf(sock: socket.socket) -> bytearray | None:
    """Buffer-returning variant: the payload lands in a fresh bytearray
    that is returned as-is — large frames skip the trailing ``bytes()``
    copy, and memoryview slices of it feed zero-copy ingest."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ConnectionError(f"frame of {n} bytes exceeds sanity bound")
    body = _recv_exact_buf(sock, n)
    if body is None:
        raise ConnectionError("connection closed mid-frame")
    return body


def recv_reply(sock: socket.socket, sink_for=None):
    """One reply frame, demultiplexed AT THE WIRE: ``(req_id, ok,
    payload)``, or None on clean EOF.

    Raw frames skip the codec; additionally, when ``sink_for(req_id,
    payload_len)`` returns a writable buffer, the payload bytes are
    received STRAIGHT into it — kernel to final home, no intermediate
    frame buffer — and the returned ``RawReply.payload`` is None to
    mean "already landed in your sink".  ``sink_for`` returning None
    (wrong length, no sink registered, non-shm ingest) falls back to
    the buffered receive.  Pickled control frames deserialize as
    before."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ConnectionError(f"frame of {n} bytes exceeds sanity bound")
    if n == 0:
        raise ConnectionError("empty reply frame")
    first = _recv_exact(sock, 1)
    if first is None:
        raise ConnectionError("connection closed mid-frame")
    if first[0] != RAW_MARKER:
        # pickled control frame: reassemble around the consumed byte
        buf = bytearray(n)
        buf[0] = first[0]
        if n > 1:
            _recv_into_exact(sock, memoryview(buf)[1:])
        return deserialize(buf)
    rest = _recv_exact(sock, _RAW_HDR.size - 1)
    if rest is None:
        raise ConnectionError("connection closed mid-frame")
    req_id, flags, meta_len = _RAW_HDR_REST.unpack(rest)
    ok = bool(flags & 1)
    meta = None
    if meta_len:
        meta_bytes = _recv_exact(sock, meta_len)
        if meta_bytes is None:
            raise ConnectionError("connection closed mid-frame")
        meta = deserialize(meta_bytes)
    payload_len = n - _RAW_HDR.size - meta_len
    sink = (sink_for(req_id, payload_len)
            if ok and sink_for is not None else None)
    if sink is not None:
        _recv_into_exact(sock, sink if isinstance(sink, memoryview)
                         else memoryview(sink))
        return req_id, ok, RawReply(meta, None)
    body = _recv_exact_buf(sock, payload_len)
    if body is None:
        raise ConnectionError("connection closed mid-frame")
    return req_id, ok, RawReply(meta, memoryview(body))


# -- batched submits (the lease plane's fast-path wire format) ---------------
# One framed multi-submit coalesces every worker submission drained in a
# single agent pump cycle into one upward frame: marker byte + count,
# then length-prefixed serialized entries.  0x01 cannot collide with
# either existing first byte on the channel (0x80 pickle PROTO, 0x00
# RAW_MARKER).
MULTI_SUBMIT_MARKER = 0x01
_MSUB_HDR = struct.Struct(">BI")
_MSUB_LEN = struct.Struct(">I")


def pack_multi_submit(entries) -> bytes:
    """``entries`` is a list of already-serialized frame payloads (each
    one worker ``submit`` tuple).  Returns one frame payload carrying
    them all."""
    parts = [_MSUB_HDR.pack(MULTI_SUBMIT_MARKER, len(entries))]
    for e in entries:
        parts.append(_MSUB_LEN.pack(len(e)))
        parts.append(bytes(e))
    return b"".join(parts)


def is_multi_submit(frame) -> bool:
    return len(frame) > 0 and frame[0] == MULTI_SUBMIT_MARKER


def unpack_multi_submit(frame) -> list[bytes]:
    """The individual serialized entries packed by ``pack_multi_submit``
    (round-trip exact: bytes in == bytes out, order preserved)."""
    _marker, count = _MSUB_HDR.unpack_from(frame, 0)
    off = _MSUB_HDR.size
    out = []
    for _ in range(count):
        (n,) = _MSUB_LEN.unpack_from(frame, off)
        off += _MSUB_LEN.size
        out.append(bytes(frame[off:off + n]))
        off += n
    if off != len(frame):
        raise ConnectionError(
            f"multi-submit frame has {len(frame) - off} trailing bytes")
    return out


def send_frame(sock: socket.socket, obj) -> None:
    send_raw_frame(sock, serialize(obj))


def recv_frame(sock: socket.socket):
    """One frame, or None on clean EOF."""
    body = recv_raw_frame(sock)
    return None if body is None else deserialize(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = _recv_exact_buf(sock, n)
    return None if buf is None else bytes(buf)


def _recv_into_exact(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` completely from the socket; a drop mid-read is
    always an error (the frame length promised these bytes)."""
    n = view.nbytes
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise ConnectionError("connection closed mid-frame")
        got += r


def _recv_exact_buf(sock: socket.socket, n: int) -> bytearray | None:
    """``n`` bytes into a fresh bytearray, None on clean EOF; a drop
    mid-read is an error — silently treating a truncated header as EOF
    would swallow a frame.

    ``recv_into`` a preallocated buffer: ``recv(n)`` with a multi-MB
    ``n`` makes CPython allocate the full request per call while the
    kernel delivers ~128KB — O(n^2) allocation across a large frame."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            if got:
                raise ConnectionError("connection closed mid-frame")
            return None
        got += r
    return buf
