"""Frame codec shared by the RPC server and client.

Frames are ``4-byte big-endian length + payload`` over a stream socket.
The raw layer (``send_raw_frame``/``recv_raw_frame``) is codec-agnostic
and shared with the cross-language gateway; this module's default codec
is cloudpickle.  Requests are ``(req_id, method, args, kwargs)``; replies
are ``(req_id, ok: bool, payload)`` where a non-ok payload is
``(exc_type_name, message, traceback_str)``.
"""

from __future__ import annotations

import socket
import struct

from ..runtime.serialization import deserialize, serialize

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024       # sanity bound, not a protocol limit


def send_raw_frame(sock: socket.socket, data: bytes) -> None:
    if len(data) > 1 << 16:
        # large frame: two sends instead of header+payload concatenation
        # (the + would copy the whole payload just to prepend 4 bytes)
        sock.sendall(_LEN.pack(len(data)))
        sock.sendall(data)
        return
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_raw_frame(sock: socket.socket) -> bytes | None:
    """One frame's payload bytes, or None on clean EOF."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ConnectionError(f"frame of {n} bytes exceeds sanity bound")
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("connection closed mid-frame")
    return body


def send_frame(sock: socket.socket, obj) -> None:
    send_raw_frame(sock, serialize(obj))


def recv_frame(sock: socket.socket):
    """One frame, or None on clean EOF."""
    body = recv_raw_frame(sock)
    return None if body is None else deserialize(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """``n`` bytes, None on clean EOF; a drop mid-read is an error —
    silently treating a truncated header as EOF would swallow a frame.

    ``recv_into`` a preallocated buffer: ``recv(n)`` with a multi-MB
    ``n`` makes CPython allocate the full request per call while the
    kernel delivers ~128KB — O(n^2) allocation across a large frame."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            if got:
                raise ConnectionError("connection closed mid-frame")
            return None
        got += r
    return bytes(buf)
