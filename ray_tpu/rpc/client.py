"""Pipelining RPC client (the retryable-gRPC-client analogue).

A single connection multiplexes concurrent calls: each call gets a
request id and parks on an event; one reader thread dispatches replies
by id.  Server-side exceptions re-raise here with the remote traceback
attached (SURVEY.md §1 layer 2).

``call_async`` exposes the same demux as explicit futures: the object
plane keeps a window of chunk requests in flight on one connection and
collects completions through ``on_done`` callbacks instead of parking
one thread per chunk.  Raw reply frames (``wire.RAW_MARKER``) resolve
to ``RawReply`` objects whose payload is a zero-copy view into the
receive buffer — no pickle pass on the bulk-data path.

Gray-failure hardening (the retryable-client part of the reference):

- **Idempotent retry** — methods named in ``retryable`` re-issue on
  timeout/connection loss with exponential backoff + FULL jitter
  (``rpc_retry_*`` knobs).  Opt-in PER METHOD: reads/stats/frees
  retry; mutations never do (an at-least-once mutation is a bug, an
  at-least-once read is a retry).
- **No hung futures** — ``close()`` and reader-thread death (clean EOF,
  network error, or an unexpected decode exception) fail every
  outstanding ``RpcFuture`` with ``RpcConnectionError``; nothing parks
  forever on a dead link.
- **Timed-out slots are reaped** — ``result(timeout)`` deregisters the
  call AND neutralizes its ``on_done``/``sink`` hooks, so a late reply
  can never fire a completion into state the caller already freed.
- **Circuit breaker** — every call outcome feeds the process-global
  per-peer breaker registry (``rpc/breaker.py``); constructing with
  ``breaker=True`` additionally fails fast (``CircuitOpenError``)
  while the peer's breaker is open, with half-open probes after the
  cooldown.
- **Chaos hooks** — when the chaos plane is armed (``rpc/chaos.py``),
  both legs consult it: requests may be dropped/duplicated/delayed at
  send, replies dropped/delayed at receive, scoped by peer address.
  One module-attribute None-check each way when chaos is off.
"""

from __future__ import annotations

import itertools
import random
import socket
import threading

from ..common import clock as _clk
from . import chaos as _chaos
from .wire import recv_reply, send_frame


class RpcConnectionError(ConnectionError):
    """The peer is gone (daemon stopped, network failure)."""


class RemoteRpcError(RuntimeError):
    """A handler raised on the server; carries the remote traceback."""

    def __init__(self, exc_type: str, message: str, remote_tb: str):
        super().__init__(f"{exc_type}: {message}")
        self.exc_type = exc_type
        self.remote_tb = remote_tb


# Sentinel distinguishing "caller said nothing" (inherit the client's
# constructor timeout) from an EXPLICIT ``timeout=None`` (block forever
# — the opt-out long gets/waits use deliberately).
_UNSET = object()


class RpcFuture:
    """One in-flight call: ``result(timeout)`` parks; ``done()`` polls.
    The ``on_done`` callback passed at issue time fires (no args, on the
    reader thread) the moment the reply — or the connection's death —
    resolves the call."""

    __slots__ = ("_client", "_req_id", "_slot", "_method")

    def __init__(self, client, req_id, slot, method):
        self._client = client
        self._req_id = req_id
        self._slot = slot
        self._method = method

    def done(self) -> bool:
        return self._slot[0].is_set()

    def wait(self, timeout=None) -> bool:
        """Park until the call resolves (reply or connection loss)
        WITHOUT raising; True when resolved.  Lets a caller that
        abandoned a call confirm no late reply is still being received
        (e.g. straight into a sink buffer it is about to free)."""
        return self._slot[0].wait(timeout)

    def result(self, timeout=None):
        slot = self._slot
        if not slot[0].wait(timeout):
            # reap: deregister AND neutralize the slot's hooks — the
            # caller is about to unwind, so a late reply must neither
            # fire on_done nor be granted a sink into freed state
            self._client._reap(self._req_id, slot)
            raise TimeoutError(
                f"rpc {self._method} timed out after {timeout}s")
        if slot[1] is None:
            raise RpcConnectionError("connection lost awaiting reply")
        if slot[1]:
            return slot[2]
        raise RemoteRpcError(*slot[2])


class RpcClient:
    def __init__(self, address: str, timeout: float = 10.0,
                 on_close=None, retryable=(), breaker: bool = False,
                 reconnect: bool = False):
        """``on_close`` fires once per connection, from the reader
        thread, when the connection drops (peer gone or local close) —
        the hook node agents/hubs use for disconnect-driven cleanup.
        ``timeout`` is both the connect deadline and the DEFAULT
        per-call deadline for ``call`` sites that don't pass their own.

        ``retryable``: method names ``call`` may transparently re-issue
        on timeout/connection loss (idempotent reads only — see module
        docstring).  ``breaker=True`` enforces the peer's circuit
        breaker (fail fast while open); outcomes are RECORDED either
        way.  ``reconnect=True`` lets a retrying ``call`` rebuild the
        underlying connection after the peer comes back."""
        self.peer_address = address
        self._default_timeout = timeout
        self._retryable = frozenset(retryable)
        self._breaker_enforce = breaker
        self._auto_reconnect = reconnect
        self._wlock = threading.Lock()
        # id -> [event, ok, payload, on_done, sink]
        self._pending: dict[int, list] = {}
        self._ids = itertools.count()
        self._closed = False
        self._user_closed = False
        self._on_close = on_close
        _chaos.ensure_env_init()
        self._sock = self._connect()
        self._reader = threading.Thread(target=self._read_loop,
                                        args=(self._sock,),
                                        daemon=True, name="rpc-reader")
        self._reader.start()

    def _connect(self) -> socket.socket:
        host, port = self.peer_address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self._default_timeout)
        sock.settimeout(None)       # calls manage their own deadlines
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    # -- retry policy --------------------------------------------------------
    def call(self, method: str, *args, timeout=_UNSET, **kwargs):
        # Omitted timeout falls back to the constructor default: a hung
        # or wedged peer fails the call instead of parking the caller
        # forever.  Pass ``timeout=None`` EXPLICITLY to wait unbounded
        # (long gets/waits that manage their own deadline).
        if timeout is _UNSET:
            timeout = self._default_timeout
        from . import breaker as _breaker
        peer = self.peer_address
        if method in self._retryable:
            from ..common.config import get_config
            cfg = get_config()
            attempts = max(1, cfg.rpc_retry_max_attempts)
            base = cfg.rpc_retry_base_ms / 1000.0
            cap = cfg.rpc_retry_max_ms / 1000.0
        else:
            attempts, base, cap = 1, 0.0, 0.0
        for attempt in range(attempts):
            if self._breaker_enforce and \
                    not _breaker.breaker_for(peer).allow():
                from .breaker import CircuitOpenError
                raise CircuitOpenError(
                    f"circuit open for peer {peer} (recent consecutive "
                    f"failures; half-open probe after cooldown)")
            try:
                result = self.call_async(method, *args, **kwargs) \
                    .result(timeout)
            except (TimeoutError, RpcConnectionError) as e:
                _breaker.record_failure(peer)
                if attempt + 1 >= attempts:
                    raise
                if self._closed:
                    if not (self._auto_reconnect and
                            self._try_reconnect()) and \
                            isinstance(e, RpcConnectionError):
                        # no path back to the peer: further attempts
                        # would fail identically without a reconnect
                        raise
                # exponential backoff with FULL jitter (decorrelates
                # retry storms from many clients hitting one gray peer)
                _clk.sleep(random.random() * min(cap, base * 2 ** attempt))
                continue
            _breaker.record_success(peer)
            return result

    def _try_reconnect(self) -> bool:
        """Rebuild the connection after loss (opt-in).  The dead
        reader is joined FIRST so its unwind (which fails every pending
        slot) can never race requests issued on the new connection."""
        with self._wlock:
            if self._user_closed or not self._closed:
                return not self._closed
            reader = self._reader
            if reader is not None and reader.is_alive():
                reader.join(timeout=5.0)
                if reader.is_alive():
                    return False
            try:
                sock = self._connect()
            except OSError:
                return False
            self._sock = sock
            self._closed = False
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock,),
                daemon=True, name="rpc-reader")
            self._reader.start()
            return True

    def call_async(self, method: str, *args, on_done=None, sink=None,
                   **kwargs) -> RpcFuture:
        """Issue without waiting; the returned future resolves when the
        reply lands.  ``on_done()`` (if given) is invoked from the
        reader thread on completion — including connection loss, so a
        windowed caller never hangs on a dead peer.

        ``sink(payload_len)`` (if given) may return a writable buffer
        for a RAW reply's payload: the bytes are then received straight
        into it on the reader thread (kernel to final home, no frame
        buffer) and the resolved ``RawReply.payload`` is None.  Return
        None from the sink to fall back to the buffered receive (e.g.
        on an unexpected length)."""
        req_id = next(self._ids)
        slot = [threading.Event(), None, None, on_done, sink]
        self._pending[req_id] = slot
        ch = _chaos._active
        act = None
        if ch is not None:
            # seeded fault decision for the request leg; a "drop" still
            # registers the slot — the call times out exactly as a
            # frame lost on a real fabric would
            act = ch.send_action(self.peer_address)
        try:
            with self._wlock:
                if self._closed:
                    raise RpcConnectionError("client is closed")
                if act != "drop":
                    send_frame(self._sock,
                               (req_id, method, args, kwargs))
                    if act == "dup":
                        send_frame(self._sock,
                                   (req_id, method, args, kwargs))
        except (OSError, ConnectionError) as e:
            self._pending.pop(req_id, None)
            raise RpcConnectionError(str(e)) from e
        return RpcFuture(self, req_id, slot, method)

    def _reap(self, req_id: int, slot: list) -> None:
        """Abandon a timed-out call: deregister its slot and strip its
        hooks, so a reply that limps in later is dropped by the demux
        (or, if the reader already holds the slot, fires into no-ops
        instead of freed caller state)."""
        self._pending.pop(req_id, None)
        slot[3] = None      # on_done
        slot[4] = None      # sink

    def _sink_for(self, req_id: int, payload_len: int):
        """Wire-level sink lookup for ``recv_reply``: the registered
        sink of the pending call, or None (buffered receive)."""
        slot = self._pending.get(req_id)
        if slot is None or slot[4] is None:
            return None
        try:
            return slot[4](payload_len)
        except Exception:   # noqa: BLE001 — a broken sink must not
            return None     # kill the reader; fall back to buffering

    def _read_loop(self, sock) -> None:
        # The unwind runs in a finally: ANY reader death — clean EOF,
        # network error, or an unexpected exception out of the codec —
        # must fail every outstanding future instead of leaving callers
        # parked forever on a thread that no longer exists.
        try:
            while True:
                try:
                    msg = recv_reply(sock, self._sink_for)
                except (ConnectionError, OSError):
                    msg = None
                if msg is None:
                    break
                ch = _chaos._active
                if ch is not None and \
                        ch.recv_action(self.peer_address) == "drop":
                    continue    # reply lost on the (simulated) fabric
                req_id, ok, payload = msg
                slot = self._pending.pop(req_id, None)
                if slot is not None:
                    slot[1], slot[2] = ok, payload
                    slot[0].set()
                    self._fire_on_done(slot)
        finally:
            self._closed = True
            # wake every waiter; they observe the unresolved slot
            # (slot[1] is None) and raise RpcConnectionError
            for slot in list(self._pending.values()):
                slot[0].set()
                self._fire_on_done(slot)
            self._pending.clear()
            if self._on_close is not None:
                try:
                    self._on_close()
                except Exception:   # noqa: BLE001 — cleanup must not
                    pass            # kill the reader's unwind

    @staticmethod
    def _fire_on_done(slot) -> None:
        cb = slot[3]
        if cb is not None:
            try:
                cb()
            except Exception:   # noqa: BLE001 — a completion hook must
                pass            # not kill the reader thread

    def close(self) -> None:
        self._user_closed = True
        self._closed = True
        # shutdown wakes our reader thread (close alone may not
        # interrupt its blocking recv), which then runs the unwind:
        # every outstanding future resolves with RpcConnectionError
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
