"""Pipelining RPC client (the retryable-gRPC-client analogue).

A single connection multiplexes concurrent calls: each call gets a
request id and parks on an event; one reader thread dispatches replies
by id.  Server-side exceptions re-raise here with the remote traceback
attached (SURVEY.md §1 layer 2).

``call_async`` exposes the same demux as explicit futures: the object
plane keeps a window of chunk requests in flight on one connection and
collects completions through ``on_done`` callbacks instead of parking
one thread per chunk.  Raw reply frames (``wire.RAW_MARKER``) resolve
to ``RawReply`` objects whose payload is a zero-copy view into the
receive buffer — no pickle pass on the bulk-data path.
"""

from __future__ import annotations

import itertools
import socket
import threading

from .wire import recv_reply, send_frame


class RpcConnectionError(ConnectionError):
    """The peer is gone (daemon stopped, network failure)."""


class RemoteRpcError(RuntimeError):
    """A handler raised on the server; carries the remote traceback."""

    def __init__(self, exc_type: str, message: str, remote_tb: str):
        super().__init__(f"{exc_type}: {message}")
        self.exc_type = exc_type
        self.remote_tb = remote_tb


# Sentinel distinguishing "caller said nothing" (inherit the client's
# constructor timeout) from an EXPLICIT ``timeout=None`` (block forever
# — the opt-out long gets/waits use deliberately).
_UNSET = object()


class RpcFuture:
    """One in-flight call: ``result(timeout)`` parks; ``done()`` polls.
    The ``on_done`` callback passed at issue time fires (no args, on the
    reader thread) the moment the reply — or the connection's death —
    resolves the call."""

    __slots__ = ("_client", "_req_id", "_slot", "_method")

    def __init__(self, client, req_id, slot, method):
        self._client = client
        self._req_id = req_id
        self._slot = slot
        self._method = method

    def done(self) -> bool:
        return self._slot[0].is_set()

    def wait(self, timeout=None) -> bool:
        """Park until the call resolves (reply or connection loss)
        WITHOUT raising; True when resolved.  Lets a caller that
        abandoned a call confirm no late reply is still being received
        (e.g. straight into a sink buffer it is about to free)."""
        return self._slot[0].wait(timeout)

    def result(self, timeout=None):
        slot = self._slot
        if not slot[0].wait(timeout):
            self._client._pending.pop(self._req_id, None)
            raise TimeoutError(
                f"rpc {self._method} timed out after {timeout}s")
        if self._client._closed and slot[1] is None:
            raise RpcConnectionError("connection lost awaiting reply")
        if slot[1]:
            return slot[2]
        raise RemoteRpcError(*slot[2])


class RpcClient:
    def __init__(self, address: str, timeout: float = 10.0,
                 on_close=None):
        """``on_close`` fires once, from the reader thread, when the
        connection drops (peer gone or local close) — the hook node
        agents/hubs use for disconnect-driven cleanup.  ``timeout`` is
        both the connect deadline and the DEFAULT per-call deadline for
        ``call`` sites that don't pass their own."""
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._default_timeout = timeout
        self._sock.settimeout(None)     # calls manage their own deadlines
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        # id -> [event, ok, payload, on_done, sink]
        self._pending: dict[int, list] = {}
        self._ids = itertools.count()
        self._closed = False
        self._on_close = on_close
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True, name="rpc-reader")
        self._reader.start()

    def call(self, method: str, *args, timeout=_UNSET, **kwargs):
        # Omitted timeout falls back to the constructor default: a hung
        # or wedged peer fails the call instead of parking the caller
        # forever.  Pass ``timeout=None`` EXPLICITLY to wait unbounded
        # (long gets/waits that manage their own deadline).
        if timeout is _UNSET:
            timeout = self._default_timeout
        return self.call_async(method, *args, **kwargs).result(timeout)

    def call_async(self, method: str, *args, on_done=None, sink=None,
                   **kwargs) -> RpcFuture:
        """Issue without waiting; the returned future resolves when the
        reply lands.  ``on_done()`` (if given) is invoked from the
        reader thread on completion — including connection loss, so a
        windowed caller never hangs on a dead peer.

        ``sink(payload_len)`` (if given) may return a writable buffer
        for a RAW reply's payload: the bytes are then received straight
        into it on the reader thread (kernel to final home, no frame
        buffer) and the resolved ``RawReply.payload`` is None.  Return
        None from the sink to fall back to the buffered receive (e.g.
        on an unexpected length)."""
        req_id = next(self._ids)
        slot = [threading.Event(), None, None, on_done, sink]
        self._pending[req_id] = slot
        try:
            with self._wlock:
                if self._closed:
                    raise RpcConnectionError("client is closed")
                send_frame(self._sock, (req_id, method, args, kwargs))
        except (OSError, ConnectionError) as e:
            self._pending.pop(req_id, None)
            raise RpcConnectionError(str(e)) from e
        return RpcFuture(self, req_id, slot, method)

    def _sink_for(self, req_id: int, payload_len: int):
        """Wire-level sink lookup for ``recv_reply``: the registered
        sink of the pending call, or None (buffered receive)."""
        slot = self._pending.get(req_id)
        if slot is None or slot[4] is None:
            return None
        try:
            return slot[4](payload_len)
        except Exception:   # noqa: BLE001 — a broken sink must not
            return None     # kill the reader; fall back to buffering

    def _read_loop(self) -> None:
        while True:
            try:
                msg = recv_reply(self._sock, self._sink_for)
            except (ConnectionError, OSError):
                msg = None
            if msg is None:
                break
            req_id, ok, payload = msg
            slot = self._pending.pop(req_id, None)
            if slot is not None:
                slot[1], slot[2] = ok, payload
                slot[0].set()
                self._fire_on_done(slot)
        self._closed = True
        # wake every waiter; they observe _closed and raise
        for slot in list(self._pending.values()):
            slot[0].set()
            self._fire_on_done(slot)
        if self._on_close is not None:
            try:
                self._on_close()
            except Exception:       # noqa: BLE001 — cleanup must not kill
                pass                # the reader's unwind

    @staticmethod
    def _fire_on_done(slot) -> None:
        cb = slot[3]
        if cb is not None:
            try:
                cb()
            except Exception:   # noqa: BLE001 — a completion hook must
                pass            # not kill the reader thread

    def close(self) -> None:
        self._closed = True
        # shutdown wakes our reader thread (close alone may not
        # interrupt its blocking recv), which then runs on_close
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
