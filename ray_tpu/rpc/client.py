"""Pipelining RPC client (the retryable-gRPC-client analogue).

A single connection multiplexes concurrent calls: each call gets a
request id and parks on an event; one reader thread dispatches replies
by id.  Server-side exceptions re-raise here with the remote traceback
attached (SURVEY.md §1 layer 2).
"""

from __future__ import annotations

import itertools
import socket
import threading

from .wire import recv_frame, send_frame


class RpcConnectionError(ConnectionError):
    """The peer is gone (daemon stopped, network failure)."""


class RemoteRpcError(RuntimeError):
    """A handler raised on the server; carries the remote traceback."""

    def __init__(self, exc_type: str, message: str, remote_tb: str):
        super().__init__(f"{exc_type}: {message}")
        self.exc_type = exc_type
        self.remote_tb = remote_tb


# Sentinel distinguishing "caller said nothing" (inherit the client's
# constructor timeout) from an EXPLICIT ``timeout=None`` (block forever
# — the opt-out long gets/waits use deliberately).
_UNSET = object()


class RpcClient:
    def __init__(self, address: str, timeout: float = 10.0,
                 on_close=None):
        """``on_close`` fires once, from the reader thread, when the
        connection drops (peer gone or local close) — the hook node
        agents/hubs use for disconnect-driven cleanup.  ``timeout`` is
        both the connect deadline and the DEFAULT per-call deadline for
        ``call`` sites that don't pass their own."""
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._default_timeout = timeout
        self._sock.settimeout(None)     # calls manage their own deadlines
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._pending: dict[int, list] = {}    # id -> [event, ok, payload]
        self._ids = itertools.count()
        self._closed = False
        self._on_close = on_close
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True, name="rpc-reader")
        self._reader.start()

    def call(self, method: str, *args, timeout=_UNSET, **kwargs):
        # Omitted timeout falls back to the constructor default: a hung
        # or wedged peer fails the call instead of parking the caller
        # forever.  Pass ``timeout=None`` EXPLICITLY to wait unbounded
        # (long gets/waits that manage their own deadline).
        if timeout is _UNSET:
            timeout = self._default_timeout
        req_id = next(self._ids)
        slot = [threading.Event(), None, None]
        self._pending[req_id] = slot
        try:
            with self._wlock:
                if self._closed:
                    raise RpcConnectionError("client is closed")
                send_frame(self._sock, (req_id, method, args, kwargs))
        except (OSError, ConnectionError) as e:
            self._pending.pop(req_id, None)
            raise RpcConnectionError(str(e)) from e
        if not slot[0].wait(timeout):
            self._pending.pop(req_id, None)
            raise TimeoutError(
                f"rpc {method} timed out after {timeout}s")
        if self._closed and slot[1] is None:
            raise RpcConnectionError("connection lost awaiting reply")
        if slot[1]:
            return slot[2]
        raise RemoteRpcError(*slot[2])

    def _read_loop(self) -> None:
        while True:
            try:
                frame = recv_frame(self._sock)
            except (ConnectionError, OSError):
                frame = None
            if frame is None:
                break
            req_id, ok, payload = frame
            slot = self._pending.pop(req_id, None)
            if slot is not None:
                slot[1], slot[2] = ok, payload
                slot[0].set()
        self._closed = True
        # wake every waiter; they observe _closed and raise
        for slot in list(self._pending.values()):
            slot[0].set()
        if self._on_close is not None:
            try:
                self._on_close()
            except Exception:       # noqa: BLE001 — cleanup must not kill
                pass                # the reader's unwind

    def close(self) -> None:
        self._closed = True
        # shutdown wakes our reader thread (close alone may not
        # interrupt its blocking recv), which then runs on_close
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
