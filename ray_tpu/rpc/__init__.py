"""Host-side control-plane RPC (TCP, length-prefixed frames).

Reference parity: upstream's control plane is gRPC/protobuf everywhere —
``src/ray/rpc/`` (``GrpcServer``, ``ClientCallManager``, retryable
clients) carrying ``NodeManagerService``/``CoreWorkerService``/
``gcs_service.proto`` (SURVEY.md §1 layer 2; mount empty).

TPU-first form: the DEVICE data plane needs no RPC at all (scheduler
state is HBM-resident, synced by XLA collectives over ICI), so the host
control plane can stay deliberately small: a threaded TCP server with
4-byte length-prefixed cloudpickle frames, request pipelining (ids +
per-request dispatch threads, so a blocking ``get`` on one request does
not stall the connection), and typed error propagation.  This carries
the driver<->head boundary (client mode, job submission) the way the
reference's gRPC carries daemon-to-daemon traffic.
"""

from .breaker import CircuitOpenError
from .client import RemoteRpcError, RpcClient, RpcConnectionError, RpcFuture
from .server import RpcServer
from .transport import (TcpTransport, Transport, connect, get_transport,
                        serve)
from .wire import RawReply, RawResult

__all__ = ["RpcServer", "RpcClient", "RpcConnectionError",
           "RemoteRpcError", "RpcFuture", "RawReply", "RawResult",
           "CircuitOpenError", "Transport", "TcpTransport",
           "get_transport", "connect", "serve"]
