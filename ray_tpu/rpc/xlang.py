"""Cross-language wire codec (the msgpack analogue).

Reference parity: the reference serializes cross-language task args and
returns with msgpack so its C++/Java frontends can exchange values with
Python workers (SURVEY.md §2.1 third-party deps: "msgpack (cross-language
serialization)"; mount empty).  Here the codec is a small self-describing
tagged binary format implemented twice — this module and
``cpp/xlang.hpp`` — so the C++ frontend speaks to the head daemon without
pickle.

Value model (the cross-language subset):

    nil | bool | int64 | float64 | bytes | str(utf-8) | list | map

Encoding: one ASCII tag byte, then a fixed- or length-prefixed payload.
All integers are big-endian.  Lengths/counts are u32.

    'N'            nil
    'T' / 'F'      true / false
    'i' + 8B       int64 (two's complement)
    'd' + 8B       float64 (IEEE-754 bits)
    'b' + u32 + n  bytes
    's' + u32 + n  str (utf-8)
    'l' + u32 + v* list
    'm' + u32 + (k v)*  map (keys are themselves values)

Python tuples encode as lists (like msgpack); dict keys may be any
encodable value.  Anything outside the subset raises
``XlangEncodeError`` — the same hard boundary the reference draws at its
msgpack layer.
"""

from __future__ import annotations

import struct

_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U32 = struct.Struct(">I")

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


class XlangEncodeError(TypeError):
    """Value is outside the cross-language subset."""


class XlangDecodeError(ValueError):
    """Malformed cross-language frame."""


def encode(value) -> bytes:
    out = bytearray()
    _enc(value, out)
    return bytes(out)


def _enc(v, out: bytearray) -> None:
    if v is None:
        out += b"N"
    elif v is True:
        out += b"T"
    elif v is False:
        out += b"F"
    elif isinstance(v, int):
        if not _INT64_MIN <= v <= _INT64_MAX:
            raise XlangEncodeError(f"int out of int64 range: {v}")
        out += b"i" + _I64.pack(v)
    elif isinstance(v, float):
        out += b"d" + _F64.pack(v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out += b"b" + _U32.pack(len(b)) + b
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out += b"s" + _U32.pack(len(b)) + b
    elif isinstance(v, (list, tuple)):
        out += b"l" + _U32.pack(len(v))
        for item in v:
            _enc(item, out)
    elif isinstance(v, dict):
        out += b"m" + _U32.pack(len(v))
        for k, val in v.items():
            _enc(k, out)
            _enc(val, out)
    else:
        raise XlangEncodeError(
            f"{type(v).__name__} is not cross-language serializable "
            "(allowed: None, bool, int, float, bytes, str, list, dict)")


def decode(data) -> object:
    value, pos = _dec(memoryview(data), 0)
    if pos != len(data):
        raise XlangDecodeError(
            f"{len(data) - pos} trailing bytes after value")
    return value


def _dec(buf: memoryview, pos: int):
    if pos >= len(buf):
        raise XlangDecodeError("truncated frame: missing tag")
    tag = buf[pos]
    pos += 1
    if tag == 0x4E:                                     # 'N'
        return None, pos
    if tag == 0x54:                                     # 'T'
        return True, pos
    if tag == 0x46:                                     # 'F'
        return False, pos
    if tag == 0x69:                                     # 'i'
        _need(buf, pos, 8)
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == 0x64:                                     # 'd'
        _need(buf, pos, 8)
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag in (0x62, 0x73):                             # 'b' / 's'
        _need(buf, pos, 4)
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        _need(buf, pos, n)
        raw = bytes(buf[pos:pos + n])
        pos += n
        if tag == 0x73:
            try:
                return raw.decode("utf-8"), pos
            except UnicodeDecodeError as e:
                raise XlangDecodeError(f"bad utf-8 in str: {e}") from e
        return raw, pos
    if tag == 0x6C:                                     # 'l'
        _need(buf, pos, 4)
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _dec(buf, pos)
            items.append(item)
        return items, pos
    if tag == 0x6D:                                     # 'm'
        _need(buf, pos, 4)
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        out = {}
        for _ in range(n):
            k, pos = _dec(buf, pos)
            if isinstance(k, (list, dict)):
                raise XlangDecodeError("unhashable map key")
            v, pos = _dec(buf, pos)
            out[k] = v
        return out, pos
    raise XlangDecodeError(f"unknown tag byte 0x{tag:02x}")


def _need(buf: memoryview, pos: int, n: int) -> None:
    if pos + n > len(buf):
        raise XlangDecodeError("truncated frame")
