"""Deterministic network-chaos plane: seeded fault injection in the RPC layer.

Real TPU-pod fabrics fail *gray* — dropped frames, tail latency,
asymmetric partitions — and gray failures are only debuggable when the
injected fault sequence is reproducible.  This module is the injection
point: a process-global chaos configuration consulted by the RPC client
(both directions), the RPC server (reply path), and the wire layer
(bandwidth pacing).  All hooks are a single module-attribute None-check
when chaos is off, so the data path pays ~nothing in production.

Determinism convention (same as ``scheduling/policy.py``): every link
gets its own pinned Philox stream, keyed by ``(seed, sha256(link))``,
and draws a fixed number of uniforms per message.  Message order *per
link* is the socket write order, so a single-threaded caller replays
bit-for-bit: the same seed reproduces the exact injected-fault trace
(``trace()``), which tests assert on.

Fault vocabulary, per message:

- **drop** — the frame is silently not sent (request) or discarded
  after receive (reply): the gray loss a retry/timeout must absorb.
- **dup** — the frame is sent twice with the same req_id (the client
  demux drops the second reply; handlers see the request twice — the
  at-least-once delivery idempotent methods must tolerate).
- **delay** — sleep ``delay_ms * (0.5 + u)`` before the send/dispatch
  (tail-latency jitter).
- **partition** ``A ↛ B`` — directed: messages toward ``dst`` are
  dropped at the sending client when ``dst`` matches the peer address
  (and ``src`` matches this process's ``identity``, default wildcard);
  a partition with ``src`` = a server's own address and ``dst='*'``
  drops that server's replies (requests arrive, answers vanish — the
  classic asymmetric gray failure).
- **bandwidth cap** — per-connection token pacing in the wire layer.

Links are named ``out:<peer>`` (requests we send to ``peer``),
``in:<peer>`` (replies we receive from ``peer``), and ``srv:<self>``
(replies a server at ``self`` sends).  Scoping is by peer address:
``links={addr: {...}}`` overrides the global probabilities for one
peer.

Control surfaces: ``Config``/env (``RT_CHAOS_*``, read once at first
RPC construction), the ``ray_tpu chaos`` CLI subcommand, and the head's
``chaos`` RPC (``control()`` is the single dispatch all three share),
so tests can partition a live cluster and heal it mid-run.
"""

from __future__ import annotations

import hashlib
import threading

from ..common import clock as _clk

__all__ = ["configure", "disable", "add_partition", "heal", "trace",
           "reset_trace", "status", "control", "active", "is_enabled",
           "ensure_env_init"]

# process-global chaos state; None == off (the fast path every hook
# checks before doing anything else)
_active = None
_install_lock = threading.Lock()
_env_inited = False

_TRACE_CAP = 20000          # per-link trace bound (memory safety)
_ACTIONS = ("drop", "dup")


def active():
    """The live ``_Chaos`` instance or None.  Hooks read this once per
    message; the None-check IS the disabled fast path."""
    return _active


def is_enabled() -> bool:
    return _active is not None


class _Link:
    """One directed link's pinned Philox stream + message counter +
    fault trace.  Keyed by (seed, sha256(link name)) so the stream is a
    pure function of the seed and the link — thread interleaving across
    links cannot perturb any single link's draw sequence."""

    __slots__ = ("rng", "n", "trace", "lock")

    def __init__(self, seed: int, name: str):
        import numpy as np
        digest = hashlib.sha256(name.encode()).digest()
        k0 = int.from_bytes(digest[:8], "big")
        k1 = int.from_bytes(digest[8:16], "big") ^ (seed & (2**64 - 1))
        self.rng = np.random.Generator(np.random.Philox(key=[k0, k1]))
        self.n = 0              # messages decided on this link
        self.trace: list = []   # (msg_index, action) injected faults
        self.lock = threading.Lock()


class _Params:
    __slots__ = ("drop_p", "dup_p", "delay_p", "delay_ms")

    def __init__(self, drop_p=0.0, dup_p=0.0, delay_p=0.0, delay_ms=0.0):
        self.drop_p = float(drop_p)
        self.dup_p = float(dup_p)
        self.delay_p = float(delay_p)
        self.delay_ms = float(delay_ms)


class _Chaos:
    def __init__(self, seed: int = 0, drop_p: float = 0.0,
                 dup_p: float = 0.0, delay_p: float = 0.0,
                 delay_ms: float = 0.0, bandwidth_mbps: float = 0.0,
                 links: dict | None = None, identity: str = "*"):
        self.seed = int(seed)
        self.defaults = _Params(drop_p, dup_p, delay_p, delay_ms)
        self.bandwidth_mbps = float(bandwidth_mbps)
        # peer address -> _Params override (scoped per-link knobs)
        self.links = {a: _Params(**d) for a, d in (links or {}).items()}
        self.identity = identity or "*"
        # directed partitions: set of (src, dst); "*" wildcards
        self.partitions: set = set()
        self._streams: dict = {}
        self._streams_lock = threading.Lock()
        # bandwidth pacing: per-socket next-free-time accounting
        self._pace_lock = threading.Lock()
        self._pace_next: dict = {}
        # counters
        self.num_dropped = 0
        self.num_duplicated = 0
        self.num_delayed = 0
        self.num_partitioned = 0

    # -- decisions -----------------------------------------------------------
    def _params_for(self, addr: str) -> _Params:
        return self.links.get(addr, self.defaults)

    def _link(self, name: str) -> _Link:
        # Double-checked lazy init: the unlocked probe is a benign race
        # (dict get is atomic; losers re-check under _streams_lock).
        link = self._streams.get(name)  # rtlint: disable=W7
        if link is None:
            with self._streams_lock:
                link = self._streams.get(name)
                if link is None:
                    link = self._streams[name] = _Link(self.seed, name)
        return link

    def _keys_snapshot(self) -> list:
        with self._streams_lock:
            return list(self._streams)

    def _partitioned(self, src: str, dst: str) -> bool:
        # Existential match over the set: the answer is the same
        # whatever order the pairs come out in, and nothing else in the
        # loop draws or traces.
        for a, b in self.partitions:  # rtlint: disable=W8
            if (a == "*" or a == src) and (b == "*" or b == dst):
                return True
        return False

    def _decide(self, link_name: str, addr: str) -> str | None:
        """One seeded decision: returns "drop"/"dup"/None and sleeps the
        delay (if drawn) before returning.  A FIXED number of draws per
        message keeps the stream aligned across replays regardless of
        which faults fire."""
        p = self._params_for(addr)
        link = self._link(link_name)
        with link.lock:
            n = link.n
            link.n += 1
            u = link.rng.random(4)
            action = None
            if u[0] < p.drop_p:
                action = "drop"
            elif u[1] < p.dup_p:
                action = "dup"
            delay = 0.0
            if p.delay_ms > 0 and u[2] < p.delay_p:
                delay = p.delay_ms * (0.5 + float(u[3])) / 1000.0
            if (action or delay) and len(link.trace) < _TRACE_CAP:
                tag = action or ""
                if delay:
                    tag = (tag + "+" if tag else "") + \
                        f"delay:{delay * 1000:.3f}"
                link.trace.append((n, tag))
        # Deliberately-racy monotonic gauges: a lost increment only
        # undercounts diagnostics; the replayed fault schedule itself is
        # carried by the per-link Philox stream, not these counters.
        if action == "drop":
            self.num_dropped += 1  # rtlint: disable=W7
        elif action == "dup":
            self.num_duplicated += 1  # rtlint: disable=W7
        if delay:
            self.num_delayed += 1  # rtlint: disable=W7
            _clk.sleep(delay)
        return action

    def send_action(self, peer: str) -> str | None:
        """Client -> server request leg (link ``out:<peer>``)."""
        if self._partitioned(self.identity, peer):
            self.num_partitioned += 1  # rtlint: disable=W7 — monotonic gauge
            link = self._link(f"out:{peer}")
            with link.lock:
                n = link.n
                link.n += 1
                if len(link.trace) < _TRACE_CAP:
                    link.trace.append((n, "part"))
            return "drop"
        return self._decide(f"out:{peer}", peer)

    def recv_action(self, peer: str) -> str | None:
        """Server -> client reply leg, decided at the receiving client
        (link ``in:<peer>``).  "dup" is meaningless here (the demux
        drops unsolicited replies) — treat it as None."""
        act = self._decide(f"in:{peer}", peer)
        return act if act == "drop" else None

    def reply_action(self, self_addr: str) -> str | None:
        """Server reply leg, decided at the sending server (link
        ``srv:<self>``): how an asymmetric partition (requests arrive,
        replies vanish) is injected."""
        if self._partitioned(self_addr, "*"):
            self.num_partitioned += 1  # rtlint: disable=W7 — monotonic gauge
            link = self._link(f"srv:{self_addr}")
            with link.lock:
                n = link.n
                link.n += 1
                if len(link.trace) < _TRACE_CAP:
                    link.trace.append((n, "part"))
            return "drop"
        return self._decide(f"srv:{self_addr}", self_addr)

    def link_action(self, src: str, dst: str) -> str | None:
        """Virtual-link leg for the in-process simulator: one seeded
        decision on directed link ``src->dst``.  Same Philox keying and
        fixed draw count as the socket legs, so a simulated campaign's
        drop/dup/delay schedule replays bit-for-bit from the seed.
        Per-peer ``links`` overrides and directed partitions key by
        ``dst`` / ``(src, dst)`` exactly like the socket path."""
        if self._partitioned(src, dst):
            self.num_partitioned += 1  # rtlint: disable=W7 — monotonic gauge
            link = self._link(f"{src}->{dst}")
            with link.lock:
                n = link.n
                link.n += 1
                if len(link.trace) < _TRACE_CAP:
                    link.trace.append((n, "part"))
            return "drop"
        return self._decide(f"{src}->{dst}", dst)

    # -- bandwidth pacing (wire seam) ----------------------------------------
    def pace(self, sock, nbytes: int) -> None:
        """Token pacing per connection: sending ``nbytes`` reserves
        ``nbytes / rate`` seconds of the link; a send finding the link
        busy sleeps until its reservation starts."""
        rate = self.bandwidth_mbps * 1e6 / 8.0      # bytes/sec
        if rate <= 0 or nbytes <= 0:
            return
        # Process-local pacing key only: never traced, never hashed
        # into the schedule; id() is just a cheap per-connection handle.
        key = id(sock)  # rtlint: disable=W8
        now = _clk.monotonic()
        with self._pace_lock:
            if len(self._pace_next) > 512:          # bound stale entries
                self._pace_next = {k: v for k, v in
                                   self._pace_next.items() if v > now}
            start = max(now, self._pace_next.get(key, 0.0))
            self._pace_next[key] = start + nbytes / rate
        if start > now:
            _clk.sleep(start - now)

    # -- introspection -------------------------------------------------------
    def trace(self) -> dict:
        with self._streams_lock:
            return {name: list(link.trace)
                    for name, link in self._streams.items() if link.trace}

    def reset_trace(self) -> None:
        """Drop streams AND traces: the next message on every link
        replays from draw 0 (how tests assert seed-reproducibility)."""
        with self._streams_lock:
            self._streams.clear()

    def status(self) -> dict:
        d = self.defaults
        return {
            "enabled": True,
            "seed": self.seed,
            "drop_p": d.drop_p,
            "dup_p": d.dup_p,
            "delay_p": d.delay_p,
            "delay_ms": d.delay_ms,
            "bandwidth_mbps": self.bandwidth_mbps,
            "identity": self.identity,
            "partitions": sorted(self.partitions),
            "links": sorted(self._keys_snapshot()),
            "num_dropped": self.num_dropped,
            "num_duplicated": self.num_duplicated,
            "num_delayed": self.num_delayed,
            "num_partitioned": self.num_partitioned,
        }


# -- module-level control ----------------------------------------------------
def _install(chaos) -> None:
    global _active
    from . import wire
    with _install_lock:
        _active = chaos
        wire._chaos_pacer = chaos.pace if chaos is not None else None


def configure(seed: int = 0, drop_p: float = 0.0, dup_p: float = 0.0,
              delay_p: float = 0.0, delay_ms: float = 0.0,
              bandwidth_mbps: float = 0.0, links: dict | None = None,
              identity: str = "*") -> dict:
    """Install a fresh chaos configuration (replacing any previous one;
    streams restart from draw 0).  Returns ``status()``."""
    chaos = _Chaos(seed=seed, drop_p=drop_p, dup_p=dup_p,
                   delay_p=delay_p, delay_ms=delay_ms,
                   bandwidth_mbps=bandwidth_mbps, links=links,
                   identity=identity)
    _install(chaos)
    return chaos.status()


def disable() -> dict:
    _install(None)
    return {"enabled": False}


def add_partition(src: str = "*", dst: str = "*") -> dict:
    """Directed partition ``src ↛ dst`` (addresses or "*").  Installs a
    default (fault-free) config first if chaos is off, so a partition
    alone needs no probabilities."""
    ch = _active
    if ch is None:
        configure()
        ch = _active
    ch.partitions.add((src, dst))
    return ch.status()


def heal(src: str | None = None, dst: str | None = None) -> dict:
    """Remove matching partitions (all of them when src and dst are
    both None)."""
    ch = _active
    if ch is None:
        return {"enabled": False}
    if src is None and dst is None:
        ch.partitions.clear()
    else:
        ch.partitions = {(a, b) for a, b in ch.partitions
                         if not ((src is None or a == src) and
                                 (dst is None or b == dst))}
    return ch.status()


def trace() -> dict:
    ch = _active
    return ch.trace() if ch is not None else {}


def reset_trace() -> None:
    ch = _active
    if ch is not None:
        ch.reset_trace()


def status() -> dict:
    ch = _active
    return ch.status() if ch is not None else {"enabled": False}


def control(op: str, **kwargs) -> dict:
    """Single dispatch shared by the head RPC and the CLI:
    ``set`` (configure), ``partition``, ``heal``, ``status``,
    ``trace``, ``reset_trace``, ``off``."""
    if op == "set":
        return configure(**kwargs)
    if op == "partition":
        return add_partition(kwargs.get("src", "*"),
                             kwargs.get("dst", "*"))
    if op == "heal":
        return heal(kwargs.get("src"), kwargs.get("dst"))
    if op == "status":
        return status()
    if op == "trace":
        return {"trace": trace()}
    if op == "reset_trace":
        reset_trace()
        return {"ok": True}
    if op == "off":
        return disable()
    raise ValueError(f"unknown chaos op {op!r}")


def ensure_env_init() -> None:
    """One-time config/env activation (``RT_CHAOS_ENABLED=1`` + the
    ``chaos_*`` knobs), checked lazily at first RPC endpoint creation
    so the common no-chaos path costs one global bool test."""
    global _env_inited
    if _env_inited:
        return
    _env_inited = True
    try:
        from ..common.config import get_config
        cfg = get_config()
    except Exception:   # noqa: BLE001 — config unavailable: stay off
        return
    if getattr(cfg, "chaos_enabled", False):
        configure(seed=cfg.chaos_seed, drop_p=cfg.chaos_drop_p,
                  dup_p=cfg.chaos_dup_p, delay_p=cfg.chaos_delay_p,
                  delay_ms=cfg.chaos_delay_ms,
                  bandwidth_mbps=cfg.chaos_bandwidth_mbps)
