"""Threaded TCP RPC server (the GrpcServer analogue).

One reader thread per connection; each REQUEST runs on its own worker
thread so a long/blocking handler (``ray.get``) never stalls the other
requests pipelined on the same connection — the same property gRPC's
completion queues give the reference (SURVEY.md §1 layer 2).
"""

from __future__ import annotations

import socket
import threading
import traceback

from .wire import recv_frame, send_frame


class RpcServer:
    def __init__(self, handlers: dict, host: str = "127.0.0.1",
                 port: int = 0):
        """``handlers``: method name -> callable(*args, **kwargs).
        ``port=0`` picks a free port (read it from ``self.address``)."""
        self._handlers = dict(handlers)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stopped = False
        self._conns: set = set()
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "RpcServer":
        self._accept_thread.start()
        return self

    def add_handler(self, name: str, fn) -> None:
        self._handlers[name] = fn

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return          # socket closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # replies from concurrent handler threads interleave on one
        # socket: serialize the WRITES, never the handlers
        wlock = threading.Lock()
        try:
            while True:
                try:
                    frame = recv_frame(conn)
                except (ConnectionError, OSError):
                    return
                if frame is None:
                    return
                req_id, method, args, kwargs = frame
                threading.Thread(
                    target=self._run_handler,
                    args=(conn, wlock, req_id, method, args, kwargs),
                    daemon=True, name=f"rpc-h-{method}").start()
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _run_handler(self, conn, wlock, req_id, method, args,
                     kwargs) -> None:
        try:
            fn = self._handlers.get(method)
            if fn is None:
                raise AttributeError(f"no rpc method {method!r}")
            result = fn(*args, **kwargs)
            ok, payload = True, result
        except BaseException as e:     # noqa: BLE001 — typed error reply
            ok, payload = False, (type(e).__name__, str(e),
                                  traceback.format_exc())
        try:
            with wlock:
                send_frame(conn, (req_id, ok, payload))
        except (OSError, ConnectionError):
            pass                # client went away; nothing to tell it

    def stop(self) -> None:
        self._stopped = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
