"""Threaded TCP RPC server (the GrpcServer analogue).

One reader thread per connection; each REQUEST runs on its own worker
thread so a long/blocking handler (``ray.get``) never stalls the other
requests pipelined on the same connection — the same property gRPC's
completion queues give the reference (SURVEY.md §1 layer 2).

The connection lifecycle is codec-agnostic: subclasses swap the frame
codec and request/reply shapes via the ``_decode_request`` /
``_encode_reply`` / ``_error_payload`` / ``_invoke`` hooks (the
cross-language gateway reuses everything but the pickle codec —
``rpc/xlang_gateway.py``).  Replies are encoded OUTSIDE the write lock,
and an encode failure is itself sent as a typed error reply — a payload
the codec rejects must never leave a synchronous client blocked waiting
for a reply that died on the server.
"""

from __future__ import annotations

import logging
import socket
import threading
import traceback

from . import chaos as _chaos
from .wire import (RawResult, recv_raw_frame, send_raw_frame,
                   send_raw_reply)

_LOG = logging.getLogger("ray_tpu.rpc.server")


class RpcServer:
    def __init__(self, handlers: dict, host: str = "127.0.0.1",
                 port: int = 0):
        """``handlers``: method name -> callable(*args, **kwargs).
        ``port=0`` picks a free port (read it from ``self.address``)."""
        self._handlers = dict(handlers)
        # per-method wire accounting: method -> [bytes_in, bytes_out].
        # Tests use this to PROVE data-plane payloads bypass a server
        # (e.g. object transfers never transiting the head).
        self.method_bytes: dict = {}
        # per-method REQUEST counts (same proof role as the bytes:
        # e.g. asserting N local leases cost O(1) head calls)
        self.method_calls: dict = {}
        self._mb_lock = threading.Lock()
        # per-connection cleanup callbacks (registered by handlers via
        # on_conn_close while serving a request on that connection) —
        # how the head ties client-session state to connection lifetime
        self._conn_cleanups: dict = {}
        self._tls = threading.local()
        _chaos.ensure_env_init()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stopped = False
        self._conns: set = set()
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "RpcServer":
        self._accept_thread.start()
        return self

    def add_handler(self, name: str, fn) -> None:
        self._handlers[name] = fn

    def on_conn_close(self, callback) -> bool:
        """Run ``callback()`` when the CURRENT request's connection
        drops (clean close or network death).  Callable only from
        inside a handler; returns False outside one."""
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            return False
        with self._lock:
            if conn not in self._conns:
                return False    # already gone: run it now
            self._conn_cleanups.setdefault(conn, []).append(callback)
            return True

    # -- codec hooks (pickle protocol; overridden by the xlang gateway) ----
    def _recv_request(self, conn):
        """One request frame (raw bytes here; the decode hook parses),
        or None on clean EOF."""
        return recv_raw_frame(conn)

    def _decode_request(self, frame):
        """frame -> (req_id, method, args, kwargs), or None to drop the
        connection on a protocol violation."""
        from ..runtime.serialization import deserialize
        req_id, method, args, kwargs = deserialize(frame)
        return req_id, method, args, kwargs

    def _account(self, method: str, n_in: int, n_out: int) -> None:
        with self._mb_lock:
            row = self.method_bytes.get(method)
            if row is None:
                row = self.method_bytes[method] = [0, 0]
            row[0] += n_in
            row[1] += n_out
            if n_in:        # request leg only (replies re-account out)
                self.method_calls[method] = \
                    self.method_calls.get(method, 0) + 1

    def total_bytes(self, exclude: tuple = ()) -> int:
        """Sum of request+reply wire bytes across methods (minus any in
        ``exclude``)."""
        with self._mb_lock:
            return sum(b_in + b_out
                       for m, (b_in, b_out) in self.method_bytes.items()
                       if m not in exclude)

    def _encode_reply(self, req_id, ok: bool, payload) -> bytes:
        from ..runtime.serialization import serialize
        return serialize((req_id, ok, payload))

    def _error_payload(self, e: BaseException):
        return (type(e).__name__, str(e), traceback.format_exc())

    def _invoke(self, fn, args, kwargs):
        return fn(*args, **kwargs)

    # -- connection lifecycle ----------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return          # socket closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # replies from concurrent handler threads interleave on one
        # socket: serialize the WRITES, never the handlers
        wlock = threading.Lock()
        try:
            while True:
                try:
                    frame = self._recv_request(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                if frame is None:
                    return
                try:
                    parsed = self._decode_request(frame)
                except (TypeError, ValueError):
                    return      # malformed request: drop the conn
                if parsed is None:
                    return
                req_id, method, args, kwargs = parsed
                if isinstance(frame, (bytes, bytearray)):
                    self._account(method, len(frame), 0)
                threading.Thread(
                    target=self._run_handler,
                    args=(conn, wlock, req_id, method, args, kwargs),
                    daemon=True, name=f"rpc-h-{method}").start()
        finally:
            with self._lock:
                self._conns.discard(conn)
                cleanups = self._conn_cleanups.pop(conn, ())
            try:
                conn.close()
            except OSError:
                pass
            for cb in cleanups:
                try:
                    cb()
                except Exception:   # noqa: BLE001 — cleanup must not
                    # kill the conn reaper, but a dying hook is a bug
                    # in its owner: keep the evidence
                    _LOG.debug("connection cleanup hook failed",
                               exc_info=True)

    def _run_handler(self, conn, wlock, req_id, method, args,
                     kwargs) -> None:
        self._tls.conn = conn
        try:
            fn = self._handlers.get(method)
            if fn is None:
                raise AttributeError(f"no rpc method {method!r}")
            result = self._invoke(fn, args, kwargs)
            ok, payload = True, result
        except BaseException as e:     # noqa: BLE001 — typed error reply
            ok, payload = False, self._error_payload(e)
        finally:
            self._tls.conn = None
        # chaos: the reply leg at the SERVER (link ``srv:<self>``) — a
        # "drop" here models the asymmetric gray failure where requests
        # arrive and execute but the answers vanish on the way back
        ch = _chaos._active
        act = ch.reply_action(self.address) if ch is not None else None
        if ok and isinstance(payload, RawResult):
            # data channel: the payload buffer (shm view / spill bytes)
            # is gather-written verbatim — no pickle, no concat copy.
            # The release hook (shm pin) runs once the socket has the
            # bytes, success or not.
            from ..runtime.serialization import serialize
            try:
                meta_bytes = serialize(payload.meta)
                if act != "drop":
                    with wlock:
                        n = send_raw_reply(conn, req_id, meta_bytes,
                                           payload.payload)
                        if act == "dup":
                            send_raw_reply(conn, req_id, meta_bytes,
                                           payload.payload)
                    self._account(method, 0, n)
            except (OSError, ConnectionError):
                pass            # client went away; nothing to tell it
            finally:
                if payload.release is not None:
                    try:
                        payload.release()
                    except Exception:   # noqa: BLE001 — pin cleanup
                        pass            # must not kill the handler
            return
        try:
            data = self._encode_reply(req_id, ok, payload)
        except Exception as e:          # result outside the codec's subset
            ok = False
            data = self._encode_reply(req_id, False,
                                      self._error_payload(e))
        if act == "drop":
            return              # reply lost on the (simulated) fabric
        self._account(method, 0, len(data))
        try:
            with wlock:
                send_raw_frame(conn, data)
                if act == "dup":
                    send_raw_frame(conn, data)
        except (OSError, ConnectionError):
            pass                # client went away; nothing to tell it

    def stop(self) -> None:
        self._stopped = True
        # shutdown BEFORE close here too: close() alone does not wake a
        # thread blocked in accept(), which then lingers and can steal
        # connections if the listener fd number is later reused
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # shutdown BEFORE close: close() alone does not wake a peer
            # (or our own reader thread) blocked in recv on this socket
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
