"""Cross-language gateway: the TCP surface the C++ frontend talks to.

Reference parity: non-Python frontends in the reference reach the cluster
through the core worker's language-independent task submission path
(``cpp/`` frontend → C++ core worker — SURVEY.md §1 layer 8; mount
empty).  Here the equivalent boundary is a gateway listener on the head:
the connection lifecycle is ``rpc/server.py``'s with the codec swapped —
frames are ``u32 length + xlang value`` (``rpc/xlang.py``; no pickle
anywhere on this surface), requests are ``[req_id, method, args]``,
replies ``[req_id, ok, payload]`` with error payloads
``[exc_type, message]``.

Functions/actors are addressed by cross-language export name
(``ray_tpu/cross_language.py``); values are restricted to the xlang
subset in both directions (a handler result outside it becomes a typed
``XlangEncodeError`` reply — the base server encodes replies before
taking the write lock precisely so that failure path answers the
client).  ObjectRefs cross the wire as raw object-id bytes and take the
client-owned conservative-leak model (the gateway builds only
counter-suppressed refs, same as the pickle client mode — see
``runtime/head.py``).
"""

from __future__ import annotations

import socket

from ..common.ids import ActorID, ObjectID
from .server import RpcServer
from .wire import recv_raw_frame, send_raw_frame
from .xlang import XlangDecodeError, decode, encode


def send_xframe(sock: socket.socket, value) -> None:
    send_raw_frame(sock, encode(value))


def recv_xframe(sock: socket.socket):
    """One decoded frame, or None on clean EOF."""
    body = recv_raw_frame(sock)
    return None if body is None else decode(body)


class XlangGateway(RpcServer):
    """Serves the cross-language method set against a driver runtime."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0):
        self._rt = runtime
        super().__init__({
            "ping": self._ping,
            "put": self._put,
            "get": self._get,
            "wait": self._wait,
            "call": self._call,
            "create_actor": self._create_actor,
            "actor_call": self._actor_call,
            "kill_actor": self._kill_actor,
            "exports": self._exports,
            "cluster_resources": self._cluster_resources,
            "available_resources": self._available_resources,
        }, host=host, port=port)
        self.start()

    # -- codec hooks -------------------------------------------------------
    def _recv_request(self, conn):
        try:
            return recv_xframe(conn)
        except XlangDecodeError:
            raise ValueError("malformed xlang frame") from None

    def _decode_request(self, frame):
        if not (isinstance(frame, list) and len(frame) == 3):
            return None         # protocol violation: drop the conn
        req_id, method, args = frame
        return req_id, method, args, {}

    def _encode_reply(self, req_id, ok, payload) -> bytes:
        return encode([req_id, ok, payload])

    def _error_payload(self, e: BaseException):
        return [type(e).__name__, str(e)]

    def _invoke(self, fn, args, kwargs):
        from ..runtime.object_ref import counter_suppressed
        with counter_suppressed():  # refs built while serving a
            #                         cross-language call are
            #                         client-owned, never counted here
            return fn(*args)

    # -- method set -------------------------------------------------------
    def _ping(self):
        return {"ok": True, "exports": self._exports()}

    def _put(self, value):
        return self._rt.put_raw(value).binary()

    def _get(self, oid_bins, timeout):
        # values outside the xlang subset surface as a typed
        # XlangEncodeError reply from the base server's encode step
        return self._rt.get_raw([ObjectID(b) for b in oid_bins], timeout)

    def _wait(self, oid_bins, num_returns, timeout):
        ready, not_ready = self._rt.wait_raw(
            [ObjectID(b) for b in oid_bins], num_returns, timeout)
        return [[o.binary() for o in ready],
                [o.binary() for o in not_ready]]

    def _call(self, name, args, opts):
        fn = self._lookup(name, kind="function")
        fn = _apply_fn_opts(fn, opts or {})
        refs = fn.remote(*args)
        if not isinstance(refs, list):
            refs = [refs]
        return [r.id.binary() for r in refs]

    def _create_actor(self, name, args, opts):
        cls = self._lookup(name, kind="actor class")
        if opts:
            cls = cls.options(**_actor_opts(opts))
        handle = cls.remote(*args)
        return handle._actor_id.binary()

    def _actor_call(self, actor_bin, method, args, num_returns):
        from ..actor_api import ActorHandle, ActorMethod
        handle = ActorHandle(ActorID(actor_bin))
        n = 1 if num_returns is None else int(num_returns)
        refs = ActorMethod(handle, method, n).remote(*args)
        if not isinstance(refs, list):
            refs = [refs]
        return [r.id.binary() for r in refs]

    def _kill_actor(self, actor_bin, no_restart):
        self._rt.actor_manager.kill(ActorID(actor_bin),
                                    no_restart=bool(no_restart))

    def _exports(self):
        from .. import cross_language
        return cross_language.exports()

    def _cluster_resources(self):
        from .. import api
        return api.cluster_resources()

    def _available_resources(self):
        from .. import api
        return api.available_resources()

    def _lookup(self, name, kind):
        from .. import cross_language
        from ..actor_api import ActorClass
        from ..api import RemoteFunction
        obj = cross_language.lookup(name)
        if obj is None:
            raise KeyError(
                f"no cross-language export named {name!r} "
                f"(exports: {cross_language.exports()})")
        want = RemoteFunction if kind == "function" else ActorClass
        if not isinstance(obj, want):
            raise TypeError(f"export {name!r} is not a {kind}")
        return obj


def _apply_fn_opts(fn, opts: dict):
    kwargs = {}
    if "num_returns" in opts:
        kwargs["num_returns"] = int(opts["num_returns"])
    if "num_cpus" in opts:
        kwargs["num_cpus"] = opts["num_cpus"]
    if "resources" in opts:
        kwargs["resources"] = opts["resources"]
    if "max_retries" in opts:
        kwargs["max_retries"] = int(opts["max_retries"])
    unknown = set(opts) - {"num_returns", "num_cpus", "resources",
                           "max_retries"}
    if unknown:
        raise ValueError(f"unsupported call options: {sorted(unknown)}")
    return fn.options(**kwargs) if kwargs else fn


def _actor_opts(opts: dict) -> dict:
    allowed = {"name", "num_cpus", "resources", "max_restarts",
               "max_task_retries"}
    unknown = set(opts) - allowed
    if unknown:
        raise ValueError(f"unsupported actor options: {sorted(unknown)}")
    return dict(opts)
