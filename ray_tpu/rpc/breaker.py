"""Per-peer circuit breakers for the RPC client (gray-failure defense).

Reference parity: upstream's retryable gRPC clients back off a channel
that keeps failing, and the GCS health-check manager marks nodes it
cannot reach; the circuit-breaker form (closed → open → half-open) is
the standard shape for not hammering a peer that is timing out while
still probing for recovery.

Every ``RpcClient`` *records* call outcomes here (cheap dict updates
keyed by peer address), so the registry is a process-wide map of link
health regardless of which subsystem owns the connection.  *Enforcement*
(failing fast while a breaker is open) is opt-in per client
(``RpcClient(breaker=True)``): the data plane's peer connections use
it, while control transports with their own reconnect loops (the node
agent's head link) keep their existing semantics and only feed the
registry.

State machine per peer:

- CLOSED: normal; ``failure_threshold`` CONSECUTIVE failures open it.
- OPEN: calls fail fast with ``CircuitOpenError``; after ``reset_s``
  the next ``allow()`` admits exactly one probe (half-open).
- HALF_OPEN: the probe's success closes the breaker; its failure
  reopens it (and restarts the cooldown clock).

The registry feeds ``HealthCheckManager``: a node whose data-plane
address has an open breaker is *quarantined* — surfaced as ``suspect``,
soft-avoided by the scheduler, and demoted by the serve router.
"""

from __future__ import annotations

import threading

from ..common import clock as _clk
from .client import RpcConnectionError

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitOpenError(RpcConnectionError):
    """Fail-fast refusal: the peer's breaker is open (recent consecutive
    failures; a half-open probe will test recovery after the cooldown)."""


class PeerBreaker:
    __slots__ = ("addr", "threshold", "reset_s", "state", "failures",
                 "opened_at", "probing", "opens", "lock")

    def __init__(self, addr: str, threshold: int, reset_s: float):
        self.addr = addr
        self.threshold = max(1, int(threshold))
        self.reset_s = float(reset_s)
        self.state = CLOSED
        self.failures = 0           # consecutive
        self.opened_at = 0.0
        self.probing = False
        self.opens = 0              # cumulative open transitions
        self.lock = threading.Lock()

    def allow(self) -> bool:
        """True if a call may proceed.  While OPEN, admits exactly one
        half-open probe per cooldown expiry."""
        with self.lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if _clk.monotonic() - self.opened_at >= self.reset_s:
                    self.state = HALF_OPEN
                    self.probing = True
                    return True
                return False
            # HALF_OPEN: one probe at a time
            if self.probing:
                return False
            self.probing = True
            return True

    def record_success(self) -> None:
        with self.lock:
            self.state = CLOSED
            self.failures = 0
            self.probing = False

    def record_failure(self) -> None:
        with self.lock:
            if self.state == HALF_OPEN:
                # failed probe: straight back to OPEN, clock restarted
                self.state = OPEN
                self.opened_at = _clk.monotonic()
                self.probing = False
                self.opens += 1
                return
            self.failures += 1
            if self.state == CLOSED and self.failures >= self.threshold:
                self.state = OPEN
                self.opened_at = _clk.monotonic()
                self.opens += 1

    def snapshot(self) -> dict:
        with self.lock:
            return {"state": self.state, "failures": self.failures,
                    "opens": self.opens,
                    "open_for_s": (round(_clk.monotonic() - self.opened_at, 3)
                                   if self.state == OPEN else 0.0)}


# -- process-global registry -------------------------------------------------
_lock = threading.Lock()
_breakers: dict[str, PeerBreaker] = {}


def breaker_for(addr: str) -> PeerBreaker:
    b = _breakers.get(addr)
    if b is None:
        from ..common.config import get_config
        cfg = get_config()
        with _lock:
            b = _breakers.get(addr)
            if b is None:
                if len(_breakers) > 2048:   # ephemeral-port hygiene
                    for k in [k for k, v in _breakers.items()
                              if v.state == CLOSED and v.failures == 0]:
                        del _breakers[k]
                b = _breakers[addr] = PeerBreaker(
                    addr, cfg.rpc_breaker_failure_threshold,
                    cfg.rpc_breaker_reset_s)
    return b


def record_success(addr: str) -> None:
    b = _breakers.get(addr)
    if b is not None:
        b.record_success()


def record_failure(addr: str) -> None:
    breaker_for(addr).record_failure()


def is_open(addr: str) -> bool:
    b = _breakers.get(addr)
    return b is not None and b.state == OPEN


def open_peers() -> set:
    """Addresses whose breaker is currently OPEN (the quarantine feed
    for ``HealthCheckManager``)."""
    return {a for a, b in list(_breakers.items()) if b.state == OPEN}


def stats() -> dict:
    """Non-trivial breakers only (ever-opened or currently failing)."""
    return {a: b.snapshot() for a, b in list(_breakers.items())
            if b.opens or b.failures or b.state != CLOSED}


def reset_registry() -> None:
    """Forget every breaker (tests; a fresh cluster in-process)."""
    with _lock:
        _breakers.clear()
