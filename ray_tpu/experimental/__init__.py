"""ray_tpu.experimental — internal/advanced APIs.

Reference parity: ``ray.experimental`` hosts ``internal_kv`` (SURVEY.md
§1 layer 3; mount empty).
"""

from . import internal_kv

__all__ = ["internal_kv"]
