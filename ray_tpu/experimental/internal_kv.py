"""The GCS key-value API (namespaced bytes KV).

Reference parity: ``ray.experimental.internal_kv`` —
``_internal_kv_get/put/del/exists/list`` backed by the GCS KV manager,
used for function exports, runtime-env URIs, and library state
(``python/ray/experimental/internal_kv.py`` — SURVEY.md §1 layer 3;
mount empty).  Works from the driver and from inside tasks/actors (the
worker routes through its raylet connection).
"""

from __future__ import annotations

from ..api import _get_runtime


def _kv(op: str, key, value=None, namespace: str | None = None,
        overwrite: bool = True):
    key = key.encode() if isinstance(key, str) else bytes(key)
    if isinstance(value, str):
        value = value.encode()
    ns = namespace or ""
    rt = _get_runtime()
    if getattr(rt, "is_driver", False):
        return rt.cluster.kv.dispatch(op, key, value, ns, overwrite)
    return rt.kv_op(op, key, value, ns, overwrite)


def _internal_kv_initialized() -> bool:
    from .. import api
    return api._runtime is not None


def _internal_kv_put(key, value, overwrite: bool = True,
                     namespace: str | None = None) -> bool:
    """Returns True if the key already existed (reference semantics).
    The exists-check and write are one atomic KVStore.put — a separate
    exists probe would let two put-if-absent racers both write."""
    return bool(_kv("put", key, value, namespace=namespace,
                    overwrite=overwrite))


def _internal_kv_get(key, namespace: str | None = None) -> bytes | None:
    return _kv("get", key, namespace=namespace)


def _internal_kv_exists(key, namespace: str | None = None) -> bool:
    return bool(_kv("exists", key, namespace=namespace))


def _internal_kv_del(key, namespace: str | None = None) -> bool:
    return bool(_kv("del", key, namespace=namespace))


def _internal_kv_list(prefix, namespace: str | None = None) -> list[bytes]:
    return _kv("keys", prefix, namespace=namespace)


def _internal_kv_incr(key, delta: int = 1,
                      namespace: str | None = None) -> int:
    """Atomic counter add; returns the new value."""
    return int(_kv("incr", key, delta, namespace=namespace))
