"""Actor lifecycle + ordered method dispatch (GCS actor manager analogue).

Reference parity: ``GcsActorManager`` (registration, restart policy) +
``ActorTaskSubmitter`` (per-actor ordered queues, direct worker RPC) +
the dedicated actor worker model (``src/ray/gcs/gcs_server/
gcs_actor_manager.cc``, ``src/ray/core_worker/transport/
actor_task_submitter.cc`` — SURVEY.md §3.4; mount empty).

Model: every actor gets a DEDICATED spawned worker (reference behavior).
Method calls are strictly FIFO per actor: the head of the queue is sent
only when its ObjectRef deps are ready, preserving submission order even
when later calls' deps resolve first.  Calls pipeline onto the pipe (the
worker executes in receive order), bounded by a small in-flight window.

Restart policy: ``max_restarts`` re-runs the creation task on a fresh
worker (state is lost — reference semantics); in-flight calls at death
fail with ``ActorDiedError`` unless the actor's ``max_task_retries``
budget resubmits them; queued-not-yet-sent calls carry over.
"""

from __future__ import annotations

import enum
import threading
from collections import deque
from dataclasses import dataclass, field

from ..common.ids import ActorID, ObjectID, TaskID
from ..common.resources import ResourceRequest
from ..common.task_spec import SchedulingStrategy, SchedulingStrategyKind
from ..scheduling.policy import SchedulingOptions
from .object_ref import ObjectRef
from .serialization import (ActorDiedError, RayTaskError, deserialize,
                            serialize)
from ..common import clock as _clk

_MAX_INFLIGHT = 16          # pipelining window per actor


class ActorState(enum.Enum):
    PENDING = 0
    ALIVE = 1
    RESTARTING = 2
    DEAD = 3


@dataclass
class ActorCall:
    task_id: TaskID
    method: str
    args: tuple
    kwargs: dict
    num_returns: int
    retries_left: int = 0
    trace_ctx: tuple | None = None      # (trace_id, parent_span)
    sent_at: float = 0.0                # span start (set at send)
    group: str | None = None            # concurrency group


@dataclass
class ActorRecord:
    actor_id: ActorID
    cls_id: str
    init_args: tuple
    init_kwargs: dict
    max_restarts: int
    max_task_retries: int
    name: str | None
    resources: ResourceRequest = field(default_factory=ResourceRequest)
    strategy: SchedulingStrategy = field(
        default_factory=SchedulingStrategy)
    runtime_env: dict | None = None
    # {"max_concurrency": N, "concurrency_groups": {name: n}} — ships
    # to the worker's _ActorExecutor; widens the pipelining window so
    # a concurrent actor actually receives overlapping calls
    concurrency: dict | None = None
    # named-actor namespace ("" = the shared default namespace —
    # explicit namespaces isolate, reference ray.init(namespace=...))
    namespace: str = ""
    # "detached" actors outlive their creating job: a client disconnect
    # kills its ephemeral actors but leaves detached ones running
    # (reference lifetime="detached", GcsActorManager detached handling)
    lifetime: str = "ephemeral"
    state: ActorState = ActorState.PENDING
    worker = None
    pool = None                 # worker pool of the placement node
    row: int = -1               # placement node row (resource accounting)
    queue: deque = field(default_factory=deque)
    inflight: dict = field(default_factory=dict)    # task_id_bin -> ActorCall
    restarts_left: int = 0
    graceful_exit: bool = False


class ActorManager:
    def __init__(self, cluster):
        self._cluster = cluster
        self._store = cluster.store
        self._fn_registry = cluster.fn_registry
        self._lock = threading.RLock()
        self._actors: dict[ActorID, ActorRecord] = {}
        # (namespace, name) -> actor id
        self._names: dict[tuple[str, str], ActorID] = {}
        # streaming actor calls in flight: call task id -> actor id
        # (routes consumer acks/cancels to the actor's worker)
        self._stream_calls: dict[bytes, ActorID] = {}

    # -- creation -----------------------------------------------------------
    def create_actor(self, actor_id: ActorID, cls_id: str,
                     cls_bytes: bytes | None, args: tuple, kwargs: dict,
                     max_restarts: int, max_task_retries: int,
                     name: str | None = None,
                     resources: ResourceRequest | None = None,
                     strategy: SchedulingStrategy | None = None,
                     runtime_env: dict | None = None,
                     concurrency: dict | None = None,
                     namespace: str = "",
                     lifetime: str | None = None) -> None:
        if cls_bytes is not None:
            self._fn_registry.setdefault(cls_id, cls_bytes)
        lifetime = lifetime or "ephemeral"
        if lifetime == "detached" and name is None:
            raise ValueError(
                "detached actors must be named (reference requirement)")
        from .runtime_env import merge_runtime_env
        rec = ActorRecord(actor_id, cls_id, args, kwargs, max_restarts,
                          max_task_retries, name,
                          resources=resources or ResourceRequest(),
                          strategy=strategy or SchedulingStrategy(),
                          runtime_env=merge_runtime_env(
                              self._cluster.job_runtime_env, runtime_env),
                          concurrency=concurrency,
                          namespace=namespace or "",
                          lifetime=lifetime)
        rec.restarts_left = max_restarts
        with self._lock:
            if name is not None:
                nkey = (rec.namespace, name)
                if nkey in self._names:
                    raise ValueError(
                        f"actor name {name!r} already taken in "
                        f"namespace {rec.namespace!r}")
                self._names[nkey] = actor_id
            self._actors[actor_id] = rec
        self._resolve_then(args, lambda: self._start_incarnation(rec))

    def _resolve_then(self, args: tuple, callback) -> None:
        deps = [a.id for a in args if isinstance(a, ObjectRef)]
        missing = [d for d in deps if not self._store.contains(d)]
        if not missing:
            callback()
            return
        state = {"left": len(missing)}
        state_lock = threading.Lock()

        def on_one(_oid):
            with state_lock:
                state["left"] -= 1
                done = state["left"] == 0
            if done:
                callback()

        for d in missing:
            self._store.on_ready(d, on_one)

    def _start_incarnation(self, rec: ActorRecord) -> None:
        with self._lock:
            if rec.state is ActorState.DEAD:    # killed while pending
                return
        # placement: actors schedule like tasks, through the hybrid policy
        # over the shared resource view (reference: GcsActorScheduler uses
        # the same ClusterTaskManager lease path, SURVEY.md 3.4)
        crm = self._cluster.crm
        snapshot = crm.snapshot()
        options = SchedulingOptions()
        if rec.strategy.kind is SchedulingStrategyKind.PLACEMENT_GROUP:
            verdict, options = self._cluster.pg_manager.\
                scheduling_options_for(rec.strategy,
                                       snapshot.node_mask.shape[0])
            if verdict == "dead":
                self._on_incarnation_dead(rec.actor_id, init_error=(
                    RayTaskError("actor ctor", "placement group removed, "
                                 "unknown, or bundle index out of range",
                                 ActorDiedError())))
                return
            if verdict == "park":
                # gang member before the gang is reserved: defer until the
                # PG manager commits (its ready marker lands in the store)
                from .placement_group_manager import ready_oid_for
                self._store.on_ready(
                    ready_oid_for(rec.strategy.placement_group_id),
                    lambda _o: self._start_incarnation(rec))
                return
        req = rec.resources.dense(crm.resource_index,
                                  snapshot.totals.shape[1])
        from ..scheduling.policy import CompositeSchedulingPolicy
        row = CompositeSchedulingPolicy().schedule(snapshot, req, options)
        raylet = self._cluster.raylet_of_row(row) if row >= 0 else None
        if raylet is None:
            self._on_incarnation_dead(rec.actor_id, init_error=RayTaskError(
                "actor ctor", "no feasible node for actor resources "
                f"{rec.resources.to_dict()}", ActorDiedError()))
            return
        if not rec.resources.is_empty():
            crm.subtract(row, rec.resources)
        if rec.runtime_env:
            from .runtime_env import RuntimeEnvSetupError, env_key
            try:
                key = env_key(rec.runtime_env)
                payload = self._cluster.runtime_env_manager.get_if_ready(
                    key)
            except (RuntimeEnvSetupError, ValueError) as e:
                if not rec.resources.is_empty():
                    crm.add_back(row, rec.resources)
                self._on_incarnation_dead(
                    rec.actor_id, init_error=RayTaskError(
                        "actor ctor", f"runtime_env setup failed: {e}",
                        ActorDiedError()))
                return
            if payload is None:
                # provision off this thread: worker-submitted actor
                # creation arrives on a pool READER thread, and a
                # copytree there would stall every frame on the node
                # (get replies, results).  Release the reservation and
                # re-place once staged — the manager dedups concurrent
                # stagers of one key.
                if not rec.resources.is_empty():
                    crm.add_back(row, rec.resources)

                def provision() -> None:
                    try:
                        self._cluster.runtime_env_manager.stage(
                            rec.runtime_env)
                    except Exception:   # noqa: BLE001 — cached; the
                        pass            # retry surfaces the failure
                    self._start_incarnation(rec)
                import threading
                threading.Thread(target=provision, daemon=True,
                                 name="actor-env-stage").start()
                return
            worker = raylet.pool.spawn_dedicated(
                env_key=key, env_payload=payload)
        else:
            worker = raylet.pool.spawn_dedicated()
        worker.actor_binding = rec.actor_id
        with self._lock:
            rec.worker = worker
            rec.pool = raylet.pool
            rec.row = row
        try:
            payload = serialize((self._materialize_args(rec.init_args),
                                 rec.init_kwargs, rec.concurrency))
        except KeyError as e:
            # an init arg could not materialize at the head (its plane
            # pull failed / the object was reclaimed): fail the actor's
            # creation instead of killing this thread and leaving the
            # record PENDING forever (_on_incarnation_dead reaps the
            # dedicated worker and refunds its resources)
            self._on_incarnation_dead(rec.actor_id, init_error=RayTaskError(
                rec.cls_id, f"actor init argument unavailable: {e}"))
            return
        worker.send(("fn", rec.cls_id, self._fn_registry[rec.cls_id]))
        worker.send(("actor_new", rec.actor_id.binary(), rec.cls_id,
                     payload))

    def runtime_env_of(self, actor_id: ActorID) -> dict | None:
        """The (job-merged) env an actor runs in — children it submits
        inherit this (reference parent-inheritance semantics)."""
        with self._lock:
            rec = self._actors.get(actor_id)
            return rec.runtime_env if rec is not None else None

    def _materialize_args(self, args: tuple) -> tuple:
        # bytes living only on an agent plane pull to the head first
        # (actor args materialize head-side)
        remote = [a.id for a in args if isinstance(a, ObjectRef)
                  and self._store.plasma_info(a.id)[0] == "remote"]
        if remote:
            from .pull_manager import PullPriority
            self._cluster.pull_manager.pull_blocking(
                remote, self._cluster.head().row,
                PullPriority.TASK_ARG, None, self._store)
        out = []
        for a in args:
            if isinstance(a, ObjectRef):
                v = self._store.peek(a.id)
                out.append(v)
            else:
                out.append(a)
        return tuple(out)

    # -- method submission --------------------------------------------------
    def submit(self, actor_id: ActorID, task_id: TaskID, method: str,
               args: tuple, kwargs: dict, num_returns: int,
               trace_ctx: tuple | None = None,
               concurrency_group: str | None = None) -> None:
        if num_returns == -1:
            # streaming call: the table entry makes consumer waits
            # meaningful from submission, before any item seals
            self._cluster.task_manager.stream_open(task_id)
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None or rec.state is ActorState.DEAD:
                self._fail_call_ids(task_id, num_returns, actor_id)
                return
            call = ActorCall(task_id, method, args, kwargs, num_returns,
                             retries_left=rec.max_task_retries,
                             trace_ctx=trace_ctx,
                             group=concurrency_group)
            if num_returns == -1:
                self._stream_calls[task_id.binary()] = actor_id
            rec.queue.append(call)
        self._pump(actor_id)

    def stream_ack(self, task_id: TaskID, consumed: int) -> bool:
        """Relay a consumer ack to the worker running a streaming actor
        call (False when unknown — e.g. already finished)."""
        return self._stream_forward(task_id,
                                    ("stream_ack", task_id.binary(),
                                     consumed))

    def stream_cancel(self, task_id: TaskID) -> bool:
        return self._stream_forward(task_id,
                                    ("stream_cancel",
                                     task_id.binary()))

    def _stream_forward(self, task_id: TaskID, frame: tuple) -> bool:
        with self._lock:
            actor_id = self._stream_calls.get(task_id.binary())
            rec = self._actors.get(actor_id) if actor_id else None
            worker = rec.worker if rec is not None else None
        if worker is None:
            return False
        worker.send(frame)
        return True

    @staticmethod
    def _window(rec: ActorRecord) -> int:
        """Pipelining window: a concurrent actor must RECEIVE overlapping
        calls, so the window opens to its max_concurrency (plus group
        capacity); plain actors keep the default pipeline depth."""
        conc = rec.concurrency or {}
        want = int(conc.get("max_concurrency") or 0)
        want += sum(int(n) for n in
                    (conc.get("concurrency_groups") or {}).values())
        return max(_MAX_INFLIGHT, want)

    def _fail_call_ids(self, task_id: TaskID, num_returns: int,
                       actor_id: ActorID) -> None:
        err = RayTaskError(
            "actor task", "actor is dead",
            ActorDiedError(f"actor {actor_id.hex()[:12]} is dead"))
        self._seal_call_error(task_id, num_returns, err)

    def _seal_call_error(self, task_id: TaskID, num_returns: int,
                         err) -> None:
        """Fail one call's outputs: fixed returns seal the error;
        streaming calls finish their stream with it (waking blocked
        consumers) and drop the ack-routing entry."""
        if num_returns == -1:
            self._cluster.task_manager.stream_finished(task_id, err)
            with self._lock:
                self._stream_calls.pop(task_id.binary(), None)
            return
        for i in range(num_returns):
            self._store.put(ObjectID.for_task_return(task_id, i + 1),
                            err)

    def _pump(self, actor_id: ActorID) -> None:
        """Send queued calls in order while deps-ready and window open.

        The pop-and-send of each call happens entirely under the manager
        lock: two concurrent pumps (submitter thread + the actor's reader
        thread on completion) must not interleave their sends, or the
        worker would execute out of FIFO order.  Sends are non-blocking
        pipe writes, so holding the lock across them is cheap.
        """
        missing: list = []
        with self._lock:
            while True:
                rec = self._actors.get(actor_id)
                if rec is None or rec.state is not ActorState.ALIVE:
                    return
                if not rec.queue or \
                        len(rec.inflight) >= self._window(rec):
                    return
                call = rec.queue[0]
                deps = [a.id for a in call.args
                        if isinstance(a, ObjectRef)]
                missing = [d for d in deps
                           if not self._store.contains(d)]
                if missing:
                    break
                remote = self._remote_deps(deps)
                if remote:
                    # args whose bytes live only on an agent plane pull
                    # to the head first (actor calls materialize args
                    # head-side); the pull completion re-pumps
                    self._pull_remote_deps(remote, actor_id)
                    return
                rec.queue.popleft()
                dep_err = None
                vals = []
                for a in call.args:
                    if isinstance(a, ObjectRef):
                        v = self._store.peek(a.id)
                        if isinstance(v, RayTaskError):
                            dep_err = v
                            break
                        vals.append(v)
                    else:
                        vals.append(a)
                if dep_err is not None:
                    self._seal_call_error(call.task_id, call.num_returns, dep_err)
                    continue
                rec.inflight[call.task_id.binary()] = call
                call.sent_at = _clk.now()
                from .object_ref import (mark_transferred,
                                         transfer_generators)
                with transfer_generators() as gens:
                    payload = serialize((tuple(vals), call.kwargs,
                                         call.num_returns,
                                         call.trace_ctx, call.group))
                if rec.worker.send(("actor_call",
                                    call.task_id.binary(),
                                    call.method, payload)):
                    # only a SHIPPED frame moves stream consumption;
                    # a dead-worker send keeps the caller's close()
                    mark_transferred(gens)
        # head has missing deps: wake the pump when they land
        for d in missing:
            self._store.on_ready(d, lambda _o, a=actor_id: self._pump(a))

    def _remote_deps(self, deps) -> list:
        """Dep oids whose bytes are NOT materializable at the head: a
        metadata-only RemoteEntry means the payload lives on an agent
        plane (shm/spill entries are head-resident by definition — one
        shared store backs every simulated row)."""
        return [d for d in deps
                if self._store.plasma_info(d)[0] == "remote"]

    def _pull_remote_deps(self, oids, actor_id: ActorID) -> None:
        from .pull_manager import PullPriority
        head_row = self._cluster.head().row
        for d in oids:
            _kind, size = self._store.plasma_info(d)
            self._cluster.pull_manager.request_pull(
                d, size, head_row, PullPriority.TASK_ARG,
                callback=lambda _ok, a=actor_id: self._pump(a))

    # -- worker frame handling ---------------------------------------------
    def on_worker_message(self, worker, msg) -> bool:
        """Returns True if the frame was an actor frame and was handled."""
        kind = msg[0]
        if kind == "actor_ready":
            actor_id = ActorID(msg[1])
            doomed = None       # (pool, worker) of an actor killed mid-ctor
            with self._lock:
                rec = self._actors.get(actor_id)
                if rec is not None:
                    if rec.state is ActorState.DEAD:
                        # killed while PENDING: do not resurrect — reap the
                        # dedicated worker and return its resources
                        doomed = self._reap_worker_locked(rec)
                    else:
                        rec.state = ActorState.ALIVE
            if doomed is not None:
                self._kill_reaped(doomed)
                return True
            self._pump(actor_id)
            return True
        if kind == "actor_init_error":
            actor_id = ActorID(msg[1])
            err = deserialize(msg[2])
            self._on_incarnation_dead(actor_id, init_error=err)
            return True
        if kind in ("actor_result", "actor_result_x", "actor_error"):
            task_id_bin = msg[1]
            actor_id = getattr(worker, "actor_binding", None)
            with self._lock:
                rec = self._actors.get(actor_id) if actor_id else None
                call = rec.inflight.pop(task_id_bin, None) if rec else None
                self._stream_calls.pop(task_id_bin, None)
            if call is None:
                return True
            if call.trace_ctx is not None:
                self._cluster.events.span(
                    "actor_task", call.method[:24], call.sent_at,
                    _clk.now(), rec.row if rec is not None else -1,
                    status=kind, trace_id=call.trace_ctx[0],
                    parent_id=call.trace_ctx[1],
                    span_id=call.task_id.hex())
            contained = msg[3] if len(msg) > 3 else None
            if contained and kind in ("actor_result", "actor_result_x"):
                # refs pickled inside the results stay alive until the
                # enclosing return object is reclaimed (borrow-on-return)
                for i, inner in enumerate(contained):
                    if inner:
                        self._cluster.ref_counter.add_contained(
                            ObjectID.for_task_return(call.task_id, i + 1),
                            [ObjectID(b) for b in inner])
            if kind == "actor_result":
                row = rec.row if rec is not None else -1
                for i, data in enumerate(msg[2]):
                    oid = ObjectID.for_task_return(call.task_id, i + 1)
                    if row >= 0:
                        # pre-registered location (directory before seal —
                        # Cluster.seal_serialized rationale)
                        self._cluster.seal_serialized(oid, data, row)
                    else:
                        self._store.put_serialized(oid, data)
            elif kind == "actor_result_x":
                # plane mode: big results already sealed into the
                # actor's agent arena — metadata only (location before
                # seal); in-band bytes seal here, born on the head row.
                # "p" descriptors are handled UNCONDITIONALLY (d[1] is
                # an oid binary, never payload bytes) — rec.row can read
                # -1 when a concurrent kill raced this frame
                row = rec.row if rec is not None else -1
                head_row = self._cluster.head().row
                for i, d in enumerate(msg[2]):
                    oid = ObjectID.for_task_return(call.task_id, i + 1)
                    if d[0] == "p":
                        if row >= 0:
                            self._cluster.directory.add_location(oid, row)
                        self._store.put_remote(oid, d[2])
                    else:
                        self._cluster.seal_serialized(oid, d[1], head_row)
            else:
                err = deserialize(msg[2])
                self._seal_call_error(call.task_id, call.num_returns, err)
            if actor_id:
                self._pump(actor_id)
            return True
        if kind == "actor_exit":
            actor_id = ActorID(msg[1])
            with self._lock:
                rec = self._actors.get(actor_id)
                if rec is not None:
                    rec.graceful_exit = True
            return True
        return False

    def on_worker_death(self, worker) -> bool:
        actor_id = getattr(worker, "actor_binding", None)
        if actor_id is None:
            return False
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return True
            if rec.state is ActorState.DEAD:
                # already reaped (kill-mid-ctor / ctor failure): the reap
                # path returned resources and failed the queue
                return True
            if rec.row >= 0 and not rec.resources.is_empty():
                self._cluster.crm.add_back(rec.row, rec.resources)
                rec.row = -1
            inflight = list(rec.inflight.values())
            rec.inflight.clear()
            graceful = rec.graceful_exit
            can_restart = (not graceful) and rec.restarts_left != 0
            if can_restart and rec.restarts_left > 0:
                rec.restarts_left -= 1
            rec.state = ActorState.RESTARTING if can_restart \
                else ActorState.DEAD
            queued = None if can_restart else list(rec.queue)
            if not can_restart:
                rec.queue.clear()
                if rec.name is not None:
                    self._names.pop((rec.namespace, rec.name), None)
        # in-flight calls: retry (front of queue, original order) or fail
        err = RayTaskError(
            "actor task", "actor died",
            ActorDiedError(f"actor {actor_id.hex()[:12]} died"))
        retried = []
        for call in inflight:
            if can_restart and call.retries_left != 0:
                if call.retries_left > 0:
                    call.retries_left -= 1
                retried.append(call)
            else:
                self._seal_call_error(call.task_id, call.num_returns, err)
        if can_restart:
            with self._lock:
                for call in reversed(retried):
                    rec.queue.appendleft(call)
            self._resolve_then(rec.init_args,
                               lambda: self._restart_incarnation(rec))
        else:
            for call in (queued or []):
                self._seal_call_error(call.task_id, call.num_returns, err)
        return True

    def _restart_incarnation(self, rec: ActorRecord) -> None:
        with self._lock:
            if rec.state is not ActorState.RESTARTING:
                return
            rec.state = ActorState.PENDING
        self._start_incarnation(rec)

    def _reap_worker_locked(self, rec: ActorRecord):
        """Detach the record's dedicated worker and return its reserved
        resources to the CRM.  Caller holds the lock; returns the
        (pool, worker) pair for the caller to kill outside the lock (or
        None if there is no worker)."""
        pool, worker = rec.pool, rec.worker
        rec.worker = None
        if worker is not None and rec.row >= 0 \
                and not rec.resources.is_empty():
            self._cluster.crm.add_back(rec.row, rec.resources)
            rec.row = -1
        return (pool, worker) if worker is not None else None

    def _kill_reaped(self, doomed) -> None:
        pool, worker = doomed
        if pool is not None and worker is not None:
            pool.kill_worker(worker)

    def _on_incarnation_dead(self, actor_id: ActorID,
                             init_error=None) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            rec.state = ActorState.DEAD
            # ctor failed (or never got a node): reap the dedicated worker
            # and return reserved resources, else repeated failing actors
            # exhaust the node and leak processes
            doomed = self._reap_worker_locked(rec)
            queued = list(rec.queue)
            rec.queue.clear()
            if rec.name is not None:
                self._names.pop((rec.namespace, rec.name), None)
        if doomed is not None:
            self._kill_reaped(doomed)
        err = init_error if init_error is not None else RayTaskError(
            "actor ctor", "actor failed to start", ActorDiedError())
        for call in queued:
            self._seal_call_error(call.task_id, call.num_returns, err)

    # -- kill / lookup ------------------------------------------------------
    def kill(self, actor_id: ActorID, no_restart: bool = True) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            if no_restart:
                rec.restarts_left = 0
            worker = rec.worker if rec.state is ActorState.ALIVE else None
            # PENDING (ctor running / deps unresolved) or RESTARTING: mark
            # dead so the deferred _start/_restart_incarnation (or the
            # in-flight actor_ready) bails out; if a dedicated worker was
            # already spawned for the ctor, reap it too — otherwise the
            # process and its reserved resources leak
            if no_restart and rec.state in (ActorState.PENDING,
                                            ActorState.RESTARTING):
                doomed = self._reap_worker_locked(rec)
                worker = doomed[1] if doomed is not None else None
                self._mark_dead_locked(rec)
        if worker is not None:
            pool = rec.pool if rec.pool is not None \
                else self._cluster.head().pool
            pool.kill_worker(worker)

    def _mark_dead_locked(self, rec: ActorRecord) -> None:
        rec.state = ActorState.DEAD
        queued = list(rec.queue)
        rec.queue.clear()
        if rec.name is not None:
            self._names.pop((rec.namespace, rec.name), None)
        err = RayTaskError(
            "actor task", "actor was killed",
            ActorDiedError(f"actor {rec.actor_id.hex()[:12]} was killed"))
        for call in queued:
            self._seal_call_error(call.task_id, call.num_returns, err)

    def fail_actors_on_pool(self, pool) -> None:
        """Node removal: every actor placed on this pool loses its worker.
        The pool's shutdown suppresses reader-thread death callbacks, so
        the raylet drain calls this explicitly — restart policy applies as
        for any worker death."""
        with self._lock:
            victims = [r.worker for r in self._actors.values()
                       if r.pool is pool and r.worker is not None
                       and r.state in (ActorState.ALIVE,
                                       ActorState.PENDING)]
        for worker in victims:
            worker.dead = True
            self.on_worker_death(worker)

    def get_by_name(self, name: str,
                    namespace: str = "") -> ActorID | None:
        with self._lock:
            return self._names.get((namespace or "", name))

    def list_named(self, namespace: str | None = "") -> list[dict]:
        """Live named actors (``ray.util.list_named_actors`` parity);
        ``namespace=None`` lists every namespace."""
        with self._lock:
            out = []
            for (ns, name), aid in self._names.items():
                rec = self._actors.get(aid)
                if rec is None or rec.state is ActorState.DEAD:
                    continue
                if namespace is not None and ns != (namespace or ""):
                    continue
                out.append({"name": name, "namespace": ns,
                            "actor_id": aid.hex()})
            return out

    def on_job_exit(self, job_bin: bytes) -> None:
        """A driver/client job ended: its EPHEMERAL actors die with it;
        detached actors live until explicitly killed (reference:
        GcsActorManager destroys a job's non-detached actors on job
        death — SURVEY.md §3.4)."""
        with self._lock:
            doomed = [rec.actor_id for rec in self._actors.values()
                      if rec.lifetime != "detached"
                      and rec.state is not ActorState.DEAD
                      and rec.actor_id.job_id().binary() == job_bin]
        for actor_id in doomed:
            self.kill(actor_id, no_restart=True)

    def state_of(self, actor_id: ActorID) -> ActorState | None:
        with self._lock:
            rec = self._actors.get(actor_id)
            return rec.state if rec else None

    def named_actor_specs(self) -> list[dict]:
        """Creation specs of live NAMED actors — what a GCS snapshot
        persists so a restored head can re-create them (the reference's
        Redis-backed FT restarts detached actors from their registered
        specs; state is NOT resurrected, the ctor re-runs).  Class
        bytes travel via the fn-registry snapshot; PG strategies are
        dropped (the group does not survive the restart)."""
        from .serialization import serialize
        with self._lock:
            out = []
            for rec in self._actors.values():
                if rec.name is None or rec.state is ActorState.DEAD:
                    continue
                out.append({
                    "name": rec.name,
                    "cls_id": rec.cls_id,
                    "init": serialize((rec.init_args, rec.init_kwargs)),
                    "max_restarts": rec.max_restarts,
                    "max_task_retries": rec.max_task_retries,
                    "resources": rec.resources,
                    "runtime_env": rec.runtime_env,
                    "namespace": rec.namespace,
                    "lifetime": rec.lifetime})
            return out

    def list_actors(self) -> list[dict]:
        with self._lock:
            return [{
                "ActorID": a.hex(), "State": r.state.name,
                "Name": r.name, "Pending": len(r.queue),
                "InFlight": len(r.inflight),
            } for a, r in self._actors.items()]

    def actors_on_rows(self, rows) -> set[bytes]:
        """Actor-id binaries currently placed on the given node rows
        (the serve router demotes replicas living on SUSPECT nodes)."""
        rows = set(rows)
        if not rows:
            return set()
        with self._lock:
            return {a.binary() for a, r in self._actors.items()
                    if r.row in rows}
