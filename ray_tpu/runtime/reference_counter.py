"""Owner-side reference counting: out-of-scope objects are reclaimed.

Reference parity: the core worker's ``ReferenceCounter`` (``src/ray/
core_worker/reference_count.cc``) tracks local refs per ObjectRef (Python
``__del__``/pickle hooks) plus submitted-task dependencies, and drives
object deletion when counts hit zero; lineage stays pinned while
reconstruction might need it (SURVEY.md §1 layer 7, §5.3; mount empty).

In-process form: the driver is the owner of every object, so one counter
covers the cluster.  Task-arg borrows need no protocol — the retained
``TaskSpec`` in the TaskManager holds the arg ObjectRefs (strong Python
references), so an in-flight or lineage-pinned task keeps its deps alive
and eviction of lineage cascades naturally through ``__del__``.

``__del__`` safety: ref events are appended to a lock-free deque (atomic
in CPython) and folded by a dedicated reclaimer thread — ``__del__`` can
fire at any allocation point, including inside store/raylet critical
sections, so it must never take foreign locks.
"""

from __future__ import annotations

import threading
from collections import deque

from ..common.ids import ObjectID


class ReferenceCounter:
    def __init__(self):
        self._events: deque = deque()       # (+1 | -1, ObjectID)
        self._wake = threading.Event()
        self._counts: dict[ObjectID, int] = {}
        self._zero: set[ObjectID] = set()   # count hit 0, awaiting seal
        self._pinned: set[ObjectID] = set()
        self._reclaim = None                # callback(oid): free the object
        self._contains = None               # callback(oid) -> bool (sealed?)
        self._on_ready = None               # store.on_ready registration
        self._expects_seal = None           # callback(oid) -> bool
        self._stop = False
        self._thread: threading.Thread | None = None

    # -- hot path (any thread, __del__-safe: no locks) -----------------------
    def incref(self, object_id: ObjectID) -> None:
        self._events.append((1, object_id))

    def decref(self, object_id: ObjectID) -> None:
        self._events.append((-1, object_id))
        # wake on the empty->non-empty transition or a deep backlog: a
        # burst of dying refs (tiny-task storms) must not ping-pong the
        # GIL between this thread and the reclaimer once per event, and
        # an idle process must not poll; the periodic sweep bounds the
        # latency of events that race a concurrent flush
        n = len(self._events)
        if n == 1 or n >= 256:
            self._wake.set()

    # -- pinning (PG ready markers etc. are never reclaimed) -----------------
    def pin(self, object_id: ObjectID) -> None:
        self._events.append((0, object_id))

    def unpin(self, object_id: ObjectID) -> None:
        self._events.append((2, object_id))
        self._wake.set()

    # -- lifecycle -----------------------------------------------------------
    def attach(self, reclaim, contains, on_ready,
               expects_seal=None) -> None:
        """Start the reclaimer: ``reclaim(oid)`` frees a dead object,
        ``contains(oid)`` tests sealed-ness, ``on_ready(oid, cb)`` defers
        reclamation of not-yet-sealed objects, ``expects_seal(oid)`` says
        whether an absent object will ever seal (a pending task return
        will; a deleted put/ready-marker never will — registering a seal
        listener for those would leak a closure per object forever)."""
        self._reclaim = reclaim
        self._contains = contains
        self._on_ready = on_ready
        self._expects_seal = expects_seal
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ref-counter")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- reclaimer thread ----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        """Fold queued events and reclaim newly dead objects.  Runs on the
        reclaimer thread (tests may call it directly for determinism)."""
        dead = []
        while True:
            try:
                delta, oid = self._events.popleft()
            except IndexError:
                break
            if delta == 0:
                self._pinned.add(oid)
                continue
            if delta == 2:
                self._pinned.discard(oid)
                if self._counts.get(oid, 0) <= 0:
                    dead.append(oid)
                continue
            if delta == 3:      # recheck-after-seal (deferred reclaim)
                self._reclaim_if_still_dead(oid)
                continue
            c = self._counts.get(oid, 0) + delta
            if c > 0:
                self._counts[oid] = c
                self._zero.discard(oid)
            else:
                self._counts.pop(oid, None)
                dead.append(oid)
        for oid in dead:
            if oid in self._pinned or self._counts.get(oid, 0) > 0:
                continue
            if self._contains is not None and not self._contains(oid):
                if self._expects_seal is not None and \
                        not self._expects_seal(oid):
                    continue    # absent and never sealing: nothing to free
                # unsealed (pending task output): reclaim when it seals,
                # unless a new reference appears first
                self._zero.add(oid)
                if self._on_ready is not None:
                    self._on_ready(oid, self._recheck_on_seal)
                continue
            if self._reclaim is not None:
                self._reclaim(oid)

    def _recheck_on_seal(self, oid: ObjectID) -> None:
        """Seal callback for a deferred reclaim: routed through the event
        queue (not decided inline) so any incref already queued when the
        object seals folds FIRST — deciding here could reclaim an object
        whose new reference is still in flight."""
        self._events.append((3, oid))
        self._wake.set()

    def _reclaim_if_still_dead(self, oid: ObjectID) -> None:
        if oid in self._zero and oid not in self._pinned \
                and self._counts.get(oid, 0) <= 0:
            self._zero.discard(oid)
            if self._reclaim is not None:
                self._reclaim(oid)

    # -- introspection -------------------------------------------------------
    def count_of(self, object_id: ObjectID) -> int:
        return self._counts.get(object_id, 0)

    def stats(self) -> dict:
        return {"num_tracked": len(self._counts),
                "num_pinned": len(self._pinned),
                "queued_events": len(self._events)}
