"""Distributed reference counting: per-holder counts, owners, borrows.

Reference parity: the core worker's ``ReferenceCounter`` (``src/ray/
core_worker/reference_count.cc``) tracks local refs per process plus
borrower registrations, and the object's OWNER decides deletion;
lineage stays pinned while reconstruction might need it (SURVEY.md §1
layer 7, §5.3; mount empty).

The rebuild's shape: every ref-holding process (the driver, each worker
process, each attached client) counts its OWN refs and streams batched
incref/decref events to this table at the head — workers over their
pipe (``("refs", …)`` frames), clients over RPC (``refs_flush``).  The
head folds them per HOLDER, so one process's churn never corrupts
another's view, and a holder's death (worker crash, client disconnect)
retires all its counts at once — the fate-sharing upstream gets from
per-worker ownership.  An object stays alive while ANY holder counts it
(a worker that stashes a borrowed ref keeps the object alive after the
owner's task returns — the borrow semantics of upstream's protocol,
with the bookkeeping centralized in the GCS process like everything
else in this design).

Owner stamping: each object records the holder that created it (task
submitter / putter).  Divergence from upstream, documented: owner death
does NOT invalidate the object — the store and this table live in the
head, so surviving holders keep using it (upstream loses the object
because its metadata dies with the owning worker; ours doesn't).

Containment: a sealed result/put payload that has ObjectRefs pickled
inside it holds those inner objects alive until the ENCLOSING object is
reclaimed — closing the window where the producer's refs die before the
consumer deserializes (upstream closes it with ownership-transfer
handshakes on the serialized ref).

``__del__`` safety: ref events are appended to a lock-free deque
(atomic in CPython) and folded by a dedicated reclaimer thread —
``__del__`` can fire at any allocation point, including inside
store/raylet critical sections, so it must never take foreign locks.

Events/sec budget (measured, the centralized-fold capacity VERDICT r04
weak #3 asked to pin): the fold loop sustains ~100-140k events/s with
6-8 concurrent holder threads on the 2-core CI box (O(1) per +/- event:
running per-object totals, no per-event holder re-sum).  The queue is
unbounded by design — bursts absorb into memory and drain at fold rate;
``tests/test_ownership.py::TestOwnershipChurnStress`` asserts >20k
events/s (5x headroom for loaded CI) and prompt drain.  For
calibration, each tiny task costs ~4-6 ref events, so the fold supports
~20k tasks/s — an order of magnitude above the runtime's single-node
dispatch rate; upstream shards this load per-owner by construction
(``src/ray/core_worker/reference_count.cc``, SURVEY §2.1 — mount
empty), which is the escape hatch if a future multi-head design needs
more.
"""

from __future__ import annotations

import threading
from collections import deque

from ..common.ids import ObjectID

DRIVER = ("drv",)               # default holder: the driver process


class ReferenceCounter:
    def __init__(self):
        self._events: deque = deque()
        self._wake = threading.Event()
        # oid -> {holder: count}; an oid is live while any count > 0
        self._counts: dict[ObjectID, dict] = {}
        self._tot: dict[ObjectID, int] = {}     # running sum of counts
        #   (kept in lockstep by _bump/_retire_holder: the fold loop
        #    must not re-sum holders per event)
        self._by_holder: dict[tuple, set] = {}      # holder -> oids
        self._owner: dict[ObjectID, tuple] = {}
        self._owned_by: dict[tuple, set] = {}       # holder -> owned oids
        # retired holders: ids are never reused (client job ids and
        # worker pool indexes are monotonic), so a tombstone safely
        # drops events that raced the holder's death — a late
        # refs_flush folding after holder_gone must not resurrect
        # counts nothing will ever retire.  Bounded FIFO (a long-lived
        # head with churning clients/workers must not grow forever):
        # a tombstone only matters for the short window where a dead
        # holder's final batch is still in flight, so evicting the
        # oldest after _DEAD_HOLDER_CAP retirements is safe in practice
        self._dead_holders: set[tuple] = set()
        self._dead_holder_fifo: deque = deque()
        self._contained: dict[ObjectID, tuple] = {}  # parent -> inner oids
        self._zero: set[ObjectID] = set()   # count hit 0, awaiting seal
        self._pinned: set[ObjectID] = set()
        self._reclaim = None                # callback(oid): free the object
        self._contains = None               # callback(oid) -> bool (sealed?)
        self._on_ready = None               # store.on_ready registration
        self._expects_seal = None           # callback(oid) -> bool
        self._stop = False
        self._thread: threading.Thread | None = None
        # serializes concurrent flush() calls: the reclaimer thread and
        # direct callers (tests, teardown barriers) may fold at the same
        # time, and the batch-pop below is only safe when exactly one
        # thread pops (appends stay lock-free — __del__ never waits here)
        self._flush_lock = threading.Lock()

    # -- hot path (any thread, __del__-safe: no locks) -----------------------
    def incref(self, object_id: ObjectID, holder: tuple = DRIVER) -> None:
        self._events.append(("+", object_id, holder))

    def decref(self, object_id: ObjectID, holder: tuple = DRIVER) -> None:
        self._events.append(("-", object_id, holder))
        # wake on the empty->non-empty transition or a deep backlog: a
        # burst of dying refs (tiny-task storms) must not ping-pong the
        # GIL between this thread and the reclaimer once per event, and
        # an idle process must not poll; the periodic sweep bounds the
        # latency of events that race a concurrent flush
        n = len(self._events)
        if n == 1 or n >= 256:
            self._wake.set()

    def apply_batch(self, events, holder: tuple) -> None:
        """Fold a remote holder's batched (+1|-1, oid_bin) events —
        workers' ``("refs", …)`` frames and clients' ``refs_flush``."""
        for delta, oid_bin in events:
            self._events.append(("+" if delta > 0 else "-",
                                 ObjectID(oid_bin), holder))
        self._wake.set()

    # -- pinning (PG ready markers etc. are never reclaimed) -----------------
    def pin(self, object_id: ObjectID) -> None:
        self._events.append(("p", object_id, None))

    def unpin(self, object_id: ObjectID) -> None:
        self._events.append(("u", object_id, None))
        self._wake.set()

    # -- ownership / containment / holder lifecycle --------------------------
    def set_owner(self, object_id: ObjectID, holder: tuple) -> None:
        self._events.append(("o", object_id, holder))

    def add_contained(self, parent: ObjectID, inner) -> None:
        """Inner refs pickled inside ``parent``'s sealed payload: each
        stays alive until the parent is reclaimed."""
        if inner:
            self._events.append(("c", parent, tuple(inner)))
            self._wake.set()

    def holder_gone(self, holder: tuple) -> None:
        """A ref-holding process died/disconnected: retire every count
        it held (objects only it referenced become reclaimable)."""
        self._events.append(("g", None, holder))
        self._wake.set()

    def reconcile(self, object_id: ObjectID) -> None:
        """Re-evaluate an object's liveness through the normal dead-
        object decision path (pins, counts, seal state).  Used when an
        object is SEALED AFTER its bookkeeping might have already been
        dropped — an agent-local task's returns register at the head
        only on the batched done-sync, so a fire-and-forget caller's
        decref can fold while the head still thinks the object will
        never exist; this turns that orphan into a normal reclaim."""
        self._events.append(("z", object_id, None))
        self._wake.set()

    def force_reclaim(self, object_id: ObjectID) -> None:
        """Reclaim an orphaned object NOW regardless of counts (e.g.
        sealed-but-unconsumed stream items of a closed/stalled stream —
        no consumer ref will ever exist for them).  Routed through the
        event queue so it folds in order with in-flight events, and
        through ``_do_reclaim`` so contained refs and owner rows release
        with the object instead of leaking under the ``('obj', parent)``
        holder."""
        self._events.append(("f", object_id, None))
        self._wake.set()

    # -- lifecycle -----------------------------------------------------------
    def attach(self, reclaim, contains, on_ready,
               expects_seal=None) -> None:
        """Start the reclaimer: ``reclaim(oid)`` frees a dead object,
        ``contains(oid)`` tests sealed-ness, ``on_ready(oid, cb)`` defers
        reclamation of not-yet-sealed objects, ``expects_seal(oid)`` says
        whether an absent object will ever seal (a pending task return
        will; a deleted put/ready-marker never will — registering a seal
        listener for those would leak a closure per object forever)."""
        self._reclaim = reclaim
        self._contains = contains
        self._on_ready = on_ready
        self._expects_seal = expects_seal
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ref-counter")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- reclaimer thread ----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            try:
                self.flush()
            except Exception:   # noqa: BLE001 — the reclaimer thread
                # must survive a bad fold (a dead reclaimer leaks every
                # object from here on); the events that folded before
                # the failure are applied, the rest re-fold next wake
                import traceback
                traceback.print_exc()

    def _total(self, oid: ObjectID) -> int:
        return self._tot.get(oid, 0)

    def _bump(self, oid: ObjectID, holder: tuple, delta: int,
              dead: list) -> None:
        holders = self._counts.get(oid)
        if holders is None:
            holders = self._counts[oid] = {}
        c = holders.get(holder, 0) + delta
        if c != 0:
            holders[holder] = c
            self._by_holder.setdefault(holder, set()).add(oid)
        else:
            holders.pop(holder, None)
            hset = self._by_holder.get(holder)
            if hset is not None:
                hset.discard(oid)
                if not hset:
                    del self._by_holder[holder]
        total = self._tot.get(oid, 0) + delta
        if total > 0:
            self._tot[oid] = total
            self._zero.discard(oid)
        else:
            if holders:
                self._tot[oid] = total
            else:
                del self._counts[oid]
                self._tot.pop(oid, None)
            dead.append(oid)

    def flush(self) -> None:
        """Fold queued events and reclaim newly dead objects.  Runs on the
        reclaimer thread (tests may call it directly for determinism).
        Loops until both the queue and the dead list drain: reclaiming a
        parent enqueues decrefs for its contained refs."""
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        events = self._events
        popleft = events.popleft
        while True:
            dead = []
            processed = False
            # len() is a safe batch bound: _flush_lock makes this thread
            # the only popper, so at least that many entries exist —
            # popping by count skips a try/except per event on the hot
            # fold (the IndexError guard below is pure defense)
            while (n := len(events)):
                processed = True
                dead_holders = self._dead_holders
                bump = self._bump
                try:
                    for _ in range(n):
                        kind, oid, arg = popleft()
                        if kind == "+":
                            if arg not in dead_holders:
                                bump(oid, arg, 1, dead)
                        elif kind == "-":
                            if arg not in dead_holders:
                                bump(oid, arg, -1, dead)
                        else:
                            self._fold_rare(kind, oid, arg, dead)
                except IndexError:
                    break   # queue drained under us; fold what we have
            for oid in dead:
                if oid in self._pinned or self._total(oid) > 0:
                    continue
                if self._contains is not None and \
                        not self._contains(oid):
                    if self._expects_seal is not None and \
                            not self._expects_seal(oid):
                        self._drop_owner(oid)
                        self._release_contained(oid)
                        continue    # absent, never sealing: nothing to free
                    # unsealed (pending task output): reclaim when it
                    # seals, unless a new reference appears first
                    self._zero.add(oid)
                    if self._on_ready is not None:
                        self._on_ready(oid, self._recheck_on_seal)
                    continue
                self._do_reclaim(oid)
            if not processed and not self._events:
                return

    def _fold_rare(self, kind, oid, arg, dead) -> None:
        """Non-count events (pins, ownership, containment, holder
        retirement, forced reclaim) — off the +/- hot loop."""
        if kind == "p":
            self._pinned.add(oid)
        elif kind == "u":
            self._pinned.discard(oid)
            if self._total(oid) <= 0:
                dead.append(oid)
        elif kind == "r":       # recheck-after-seal (deferred)
            self._reclaim_if_still_dead(oid)
        elif kind == "o":
            self._owner[oid] = arg
            self._owned_by.setdefault(arg, set()).add(oid)
        elif kind == "c":
            # the parent holds its pickled-inside refs alive
            holder = ("obj", oid.binary())
            prev = self._contained.get(oid, ())
            self._contained[oid] = prev + arg
            for inner in arg:
                self._bump(inner, holder, 1, [])
        elif kind == "g":
            self._retire_holder(arg, dead)
        elif kind == "z":
            # liveness re-evaluation: the dead-processing loop applies
            # the full decision (pinned / counted / sealed / expected)
            dead.append(oid)
        elif kind == "f":
            # forced orphan reclaim: drop any stray counts so a late
            # decref cannot double-reclaim, then free
            holders = self._counts.pop(oid, None)
            self._tot.pop(oid, None)
            if holders:
                for h in list(holders):
                    hset = self._by_holder.get(h)
                    if hset is not None:
                        hset.discard(oid)
                        if not hset:
                            del self._by_holder[h]
            self._zero.discard(oid)
            self._do_reclaim(oid)

    _DEAD_HOLDER_CAP = 4096

    def _retire_holder(self, holder: tuple, dead: list) -> None:
        if holder not in self._dead_holders:
            self._dead_holders.add(holder)
            self._dead_holder_fifo.append(holder)
            while len(self._dead_holder_fifo) > self._DEAD_HOLDER_CAP:
                self._dead_holders.discard(
                    self._dead_holder_fifo.popleft())
        for oid in list(self._by_holder.get(holder, ())):
            holders = self._counts.get(oid)
            if holders is None:
                continue
            c = holders.pop(holder, 0)
            if not holders:
                del self._counts[oid]
                self._tot.pop(oid, None)
                dead.append(oid)
            else:
                if c:
                    self._tot[oid] = self._tot.get(oid, 0) - c
                if self._tot.get(oid, 0) <= 0:
                    dead.append(oid)
        self._by_holder.pop(holder, None)
        # objects OWNED by the dead holder with no counts from anyone
        # (e.g. a client that vanished before its first flush, a worker
        # whose events died in the pipe) die with it — otherwise they
        # are unreachable forever.  Survivors (counted by other holders)
        # drop their owner row entirely: the owner is gone and objects
        # outlive owner death by design here, so keeping the mapping
        # would only leak _owned_by/_owner entries on a long-lived head
        for oid in self._owned_by.pop(holder, ()):
            if self._total(oid) <= 0:
                dead.append(oid)
            else:
                self._owner.pop(oid, None)

    def _drop_owner(self, oid: ObjectID) -> None:
        owner = self._owner.pop(oid, None)
        if owner is not None:
            oset = self._owned_by.get(owner)
            if oset is not None:
                oset.discard(oid)
                if not oset:
                    del self._owned_by[owner]

    def _do_reclaim(self, oid: ObjectID) -> None:
        self._drop_owner(oid)
        self._release_contained(oid)
        if self._reclaim is not None:
            self._reclaim(oid)

    def _release_contained(self, oid: ObjectID) -> None:
        inner = self._contained.pop(oid, None)
        if inner:
            holder = ("obj", oid.binary())
            for child in inner:
                self._events.append(("-", child, holder))

    def _recheck_on_seal(self, oid: ObjectID) -> None:
        """Seal callback for a deferred reclaim: routed through the event
        queue (not decided inline) so any incref already queued when the
        object seals folds FIRST — deciding here could reclaim an object
        whose new reference is still in flight."""
        self._events.append(("r", oid, None))
        self._wake.set()

    def _reclaim_if_still_dead(self, oid: ObjectID) -> None:
        if oid in self._zero and oid not in self._pinned \
                and self._total(oid) <= 0:
            self._zero.discard(oid)
            self._do_reclaim(oid)

    # -- introspection -------------------------------------------------------
    def count_of(self, object_id: ObjectID) -> int:
        return self._total(object_id)

    def owner_of(self, object_id: ObjectID) -> tuple | None:
        return self._owner.get(object_id)

    def holders_of(self, object_id: ObjectID) -> dict:
        return dict(self._counts.get(object_id, {}))

    def stats(self) -> dict:
        return {"num_tracked": len(self._counts),
                "num_pinned": len(self._pinned),
                "num_holders": len(self._by_holder),
                "num_owned": len(self._owner),
                "queued_events": len(self._events)}
