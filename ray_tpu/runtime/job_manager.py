"""Job submission: run driver entrypoints against the head daemon.

Reference parity: ``ray job submit`` — the dashboard's job module
(``python/ray/dashboard/modules/job/``) runs the entrypoint command as a
subprocess on the head node with ``RAY_ADDRESS`` exported, captures its
output to per-job logs under the session dir, and tracks
PENDING/RUNNING/SUCCEEDED/FAILED/STOPPED status with metadata
(SURVEY.md §1 layer 15; mount empty).

Here each job runs with ``RAY_TPU_ADDRESS`` pointing back at this
daemon, so an entrypoint that calls ``ray_tpu.init(address="auto")``
attaches to the shared cluster in client mode.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
from ..common import clock as _clk


class JobInfo:
    __slots__ = ("job_id", "entrypoint", "status", "metadata",
                 "start_time", "end_time", "log_path", "proc",
                 "return_code", "runtime_env")

    def __init__(self, job_id: str, entrypoint: str, metadata: dict,
                 log_path: str, runtime_env: dict | None = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.status = "PENDING"
        self.metadata = metadata
        self.runtime_env = runtime_env
        self.start_time = _clk.now()
        self.end_time: float | None = None
        self.log_path = log_path
        self.proc: subprocess.Popen | None = None
        self.return_code: int | None = None

    def to_dict(self) -> dict:
        return {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": self.status, "metadata": self.metadata,
                "runtime_env": self.runtime_env,
                "start_time": self.start_time, "end_time": self.end_time,
                "return_code": self.return_code}


def _pid_runs_job(pid: int, job_id: str) -> bool:
    """Identity check before SIGKILLing a persisted driver pid: the OS
    may have recycled it for an unrelated process.  Drivers carry
    RAY_TPU_JOB_ID in their environment (set at submit)."""
    try:
        with open(f"/proc/{pid}/environ", "rb") as f:
            return f"RAY_TPU_JOB_ID={job_id}".encode() in f.read()
    except OSError:
        return False


class JobManager:
    def __init__(self, session_dir: str):
        self._log_dir = os.path.join(session_dir, "logs")
        os.makedirs(self._log_dir, exist_ok=True)
        self._jobs: dict[str, JobInfo] = {}
        self._lock = threading.Lock()
        self._counter = 0
        self.head_address: str | None = None    # set by HeadNode
        self._kv = None                 # GCS KV: job rows ride snapshots

    # -- persistence (head failover) ----------------------------------------
    def attach_kv(self, kv) -> None:
        """Persist job rows into the GCS KV so they ride its snapshots
        (reference: job table lives in the GCS — SURVEY.md §1 layer 3)."""
        # Single publish during head bootstrap, before any job thread
        # exists; _persist reading the slot unlocked is then safe.
        self._kv = kv  # rtlint: disable=W7

    def _persist(self, info: JobInfo) -> None:
        if self._kv is None:
            return
        import json
        row = info.to_dict()
        row["pid"] = info.proc.pid if info.proc is not None else None
        self._kv.put(info.job_id.encode(), json.dumps(row).encode(),
                     namespace="_jobs")

    def restore_jobs(self) -> list[str]:
        """After a head restart: re-run jobs that were PENDING/RUNNING
        when the old head died (their driver processes died with it, or
        are orphans we reap below).  Finished rows restore as history.
        Returns the re-submitted job ids.

        Divergence from upstream, documented: a Redis-FT GCS keeps
        raylets and drivers alive across the restart; here the runtime
        state lives in the head process, so interrupted jobs re-execute
        from their entrypoints."""
        import json
        import signal
        if self._kv is None:
            return []
        resubmitted = []
        for key in self._kv.keys(namespace="_jobs"):
            raw = self._kv.get(key, namespace="_jobs")
            if not raw:
                continue
            row = json.loads(raw)
            old_pid = row.pop("pid", None)
            if row["status"] in ("PENDING", "RUNNING"):
                if old_pid and _pid_runs_job(old_pid, row["job_id"]):
                    try:    # reap the orphaned driver of the old head
                        os.kill(old_pid, signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
                self.submit(row["entrypoint"],
                            runtime_env=row.get("runtime_env"),
                            metadata=row["metadata"],
                            job_id=row["job_id"])
                resubmitted.append(row["job_id"])
            else:
                info = JobInfo(row["job_id"], row["entrypoint"],
                               row["metadata"], os.path.join(
                                   self._log_dir,
                                   f"job-{row['job_id']}.log"),
                               runtime_env=row.get("runtime_env"))
                info.status = row["status"]
                info.start_time = row["start_time"]
                info.end_time = row["end_time"]
                info.return_code = row["return_code"]
                with self._lock:
                    self._jobs.setdefault(row["job_id"], info)
        return resubmitted

    def submit(self, entrypoint: str, runtime_env: dict | None = None,
               metadata: dict | None = None,
               job_id: str | None = None) -> str:
        cmd = shlex.split(entrypoint)
        if not cmd:
            raise ValueError("empty job entrypoint")
        if job_id is None:
            from ..common.ids import fast_random_bytes
            with self._lock:
                self._counter += 1
                suffix = fast_random_bytes(4).hex()
                job_id = f"raysubmit_{self._counter:06d}_{suffix}"
        log_path = os.path.join(self._log_dir, f"job-{job_id}.log")
        info = JobInfo(job_id, entrypoint, metadata or {}, log_path,
                       runtime_env=runtime_env)
        with self._lock:
            self._jobs[job_id] = info
        env = dict(os.environ)
        env["RAY_TPU_JOB_ID"] = job_id
        if self.head_address:
            env["RAY_TPU_ADDRESS"] = self.head_address
        # the entrypoint must resolve the SAME ray_tpu package this
        # daemon runs, wherever its cwd is (jobs inherit the cluster's
        # environment in the reference)
        import ray_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        parts = [pkg_root] + [p for p in
                              env.get("PYTHONPATH", "").split(os.pathsep)
                              if p]
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        if runtime_env and runtime_env.get("env_vars"):
            env.update(runtime_env["env_vars"])
        cwd = (runtime_env or {}).get("working_dir") or os.getcwd()
        log_f = open(log_path, "wb")
        try:
            info.proc = subprocess.Popen(
                cmd, stdout=log_f, stderr=log_f, env=env, cwd=cwd)
        except (OSError, ValueError) as e:
            log_f.write(f"failed to start: {e}\n".encode())
            log_f.close()
            info.status = "FAILED"
            info.end_time = _clk.now()
            self._persist(info)
            return job_id
        info.status = "RUNNING"
        self._persist(info)
        threading.Thread(target=self._reap, args=(info, log_f),
                         daemon=True, name=f"job-{job_id}").start()
        return job_id

    def _reap(self, info: JobInfo, log_f) -> None:
        rc = info.proc.wait()
        log_f.close()
        with self._lock:
            info.return_code = rc
            info.end_time = _clk.now()
            if info.status != "STOPPED":
                info.status = "SUCCEEDED" if rc == 0 else "FAILED"
        self._persist(info)

    def status(self, job_id: str) -> dict:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise KeyError(f"no job {job_id!r}")
        return info.to_dict()

    def list(self) -> list[dict]:
        with self._lock:
            return [j.to_dict() for j in self._jobs.values()]

    def logs(self, job_id: str) -> str:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise KeyError(f"no job {job_id!r}")
        try:
            with open(info.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise KeyError(f"no job {job_id!r}")
        if info.proc is not None and info.proc.poll() is None:
            info.status = "STOPPED"
            info.proc.terminate()
            return True
        return False

    def stop_all(self, wait: bool = False) -> None:
        with self._lock:
            jobs = list(self._jobs.values())
        for j in jobs:
            if j.proc is not None and j.proc.poll() is None:
                j.status = "STOPPED"
                j.proc.terminate()
                self._persist(j)
        if wait:
            # a final snapshot follows: terminal statuses must land in
            # the KV first, or the next start resurrects stopped jobs
            for j in jobs:
                if j.proc is not None:
                    try:
                        j.proc.wait(timeout=3.0)
                    except subprocess.TimeoutExpired:
                        j.proc.kill()

    def wait(self, job_id: str, timeout: float = 120.0) -> dict:
        """Block until the job leaves PENDING/RUNNING (test helper)."""
        deadline = _clk.monotonic() + timeout
        while _clk.monotonic() < deadline:
            st = self.status(job_id)
            if st["status"] not in ("PENDING", "RUNNING"):
                return st
            _clk.sleep(0.05)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
