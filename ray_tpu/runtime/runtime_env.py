"""Runtime environments: per-task/actor worker environment provisioning.

Reference parity: ``python/ray/_private/runtime_env/`` — a per-node agent
stages ``runtime_env`` resources (working_dir/py_modules URIs into a
local cache, pip/conda environments), workers start inside the staged
environment, and staged URIs are cached/reference-counted per node
(SURVEY.md §1 layer 10; mount empty).

In-process form: the ``RuntimeEnvManager`` stages into
``<session>/runtime_resources/<digest>/`` (content-addressed cache, the
URI-cache analogue) and produces a *payload* the spawned worker applies
at startup (env vars, chdir into the staged working_dir, sys.path for
py_modules).

``pip`` requests REALLY provision when ``runtime_env_wheelhouse``
points at a local wheel directory: requirements install offline
(``pip install --no-index --find-links <wheelhouse> --target``) into a
digest-keyed package dir that workers put on ``sys.path`` — per-env
package isolation without egress, cache hits skip the install, and an
unsatisfiable requirement fails staging with ``RuntimeEnvSetupError``
(the reference's pip plugin provisions a virtualenv the same way).
Without a wheelhouse, pip/conda requests are validated against the
already-present interpreter environment (zero-egress fallback).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading

from .serialization import RayError


class RuntimeEnvSetupError(RayError):
    """Staging a runtime_env failed (reference:
    ``ray.exceptions.RuntimeEnvSetupError``)."""


_ALLOWED_KEYS = {"env_vars", "working_dir", "py_modules", "pip", "conda"}


def normalize(env: dict | None) -> tuple | None:
    """Canonical hashable form (the worker-pool cache key).  Raises
    ValueError for ANY malformed env — including non-JSON values, which
    json.dumps reports as TypeError: callers catch ValueError to fail
    the task, and an uncaught TypeError after resource admission would
    leak the reservation every scheduling round."""
    if not env:
        return None
    unknown = set(env) - _ALLOWED_KEYS
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)}")
    try:
        return tuple(sorted(
            (k, json.dumps(env[k], sort_keys=True)) for k in env))
    except TypeError as e:
        raise ValueError(f"runtime_env is not JSON-serializable: {e}") \
            from e


def env_key(env: dict | None) -> str | None:
    norm = normalize(env)
    if norm is None:
        return None
    return hashlib.sha256(repr(norm).encode()).hexdigest()[:16]


def merge_runtime_env(job_env: dict | None,
                      task_env: dict | None) -> dict | None:
    """Task/actor env over job env; ``env_vars`` merge key-wise
    (reference runtime_env inheritance semantics).  Idempotent, so it is
    safe for a spec to cross more than one merge point."""
    if not job_env:
        return task_env
    if not task_env:
        return job_env
    merged = {**job_env, **task_env}
    if "env_vars" in job_env or "env_vars" in task_env:
        merged["env_vars"] = {**(job_env.get("env_vars") or {}),
                              **(task_env.get("env_vars") or {})}
    return merged


def _python_pin_satisfied(pin: str) -> bool:
    """Does the RUNNING interpreter satisfy a conda ``python`` pin?

    Handles the operator properly (``python>=3.8`` on 3.12 passes;
    ``python=3.1`` on 3.12 fails — component comparison, not string
    prefix).  Unparseable pins pass (don't invent failures for exotic
    conda syntax this deployment can't evaluate)."""
    import re
    import sys
    m = re.match(r"python\s*(>=|<=|==|!=|~=|=|>|<)?\s*([0-9.]+)?\s*$",
                 pin)
    if m is None or not m.group(2):
        return True
    op = m.group(1) or "="
    want = tuple(int(p) for p in m.group(2).strip(".").split("."))
    have = tuple(sys.version_info[:3])
    trunc = have[:len(want)]            # compare at the pin's precision
    if op in ("=", "==", "~="):
        # conda '=' / '~=' prefix semantics: 3.12.x matches '3.12'
        return trunc == want
    if op == "!=":
        return trunc != want
    if op == ">=":
        return trunc >= want
    if op == "<=":
        return trunc <= want
    if op == ">":
        return trunc > want
    return trunc < want                 # op == "<"


class RuntimeEnvManager:
    def __init__(self, session_dir: str):
        self._root = os.path.join(session_dir, "runtime_resources")
        self._lock = threading.Lock()
        self._cache: dict[str, dict] = {}       # key -> staged payload
        self._errors: dict[str, str] = {}       # key -> staging error
        self._inflight: dict[str, threading.Event] = {}  # key -> staging
        self.num_staged = 0
        self.num_pip_installs = 0       # real provisioning runs (cache
        #                                 hits do NOT increment)

    def get_if_ready(self, key: str | None) -> dict | None:
        """Cached payload for an env key, or None while unstaged/staging
        (the raylet's non-blocking dispatch probe).  Raises the cached
        RuntimeEnvSetupError for a known-bad env."""
        if key is None:
            return None
        with self._lock:
            if key in self._errors:
                raise RuntimeEnvSetupError(self._errors[key])
            return self._cache.get(key)

    def stage(self, env: dict | None) -> dict | None:
        """Stage (or fetch from cache) a runtime_env.  Returns the worker
        payload ``{"env_vars", "working_dir", "py_modules"}`` or None for
        the empty env.  Raises RuntimeEnvSetupError on failure (cached:
        repeated submissions fail fast like the reference's agent).
        Concurrent stagers of the same key wait for the first — two
        copytrees into one destination would hand a worker a
        half-written tree."""
        key = env_key(env)
        if key is None:
            return None
        while True:
            with self._lock:
                if key in self._errors:
                    raise RuntimeEnvSetupError(self._errors[key])
                cached = self._cache.get(key)
                if cached is not None:
                    return cached
                ev = self._inflight.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[key] = ev
                    break
            ev.wait()       # another thread is staging this key
        try:
            payload = self._stage_fresh(key, env)
        except Exception as e:
            # EVERY failure is cached and surfaced as a setup error —
            # an uncached OSError (copytree, disk full) would otherwise
            # send the async staging path into a re-stage loop
            msg = str(e) if isinstance(e, RuntimeEnvSetupError) \
                else f"{type(e).__name__}: {e}"
            with self._lock:
                self._errors[key] = msg
            raise RuntimeEnvSetupError(msg) from e
        else:
            with self._lock:
                self._cache[key] = payload
                self.num_staged += 1
            return payload
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    def _stage_fresh(self, key: str, env: dict) -> dict:
        payload: dict = {"env_vars": dict(env.get("env_vars") or {}),
                         "working_dir": None, "py_modules": []}
        for k, v in payload["env_vars"].items():
            if not isinstance(k, str) or not isinstance(v, str):
                raise RuntimeEnvSetupError(
                    f"env_vars must be str->str, got {k!r}: {v!r}")
        stage_dir = os.path.join(self._root, key)
        self._provision_pip(env, stage_dir, payload)
        wd = env.get("working_dir")
        if wd:
            if not os.path.isdir(wd):
                raise RuntimeEnvSetupError(
                    f"working_dir {wd!r} does not exist")
            dst = os.path.join(stage_dir, "working_dir")
            if not os.path.isdir(dst):
                shutil.copytree(wd, dst, dirs_exist_ok=True)
            payload["working_dir"] = dst
        for mod in env.get("py_modules") or []:
            if not os.path.exists(mod):
                raise RuntimeEnvSetupError(
                    f"py_modules entry {mod!r} does not exist")
            name = os.path.basename(mod.rstrip("/"))
            dst = os.path.join(stage_dir, "py_modules", name)
            if not os.path.exists(dst):
                if os.path.isdir(mod):
                    shutil.copytree(mod, dst, dirs_exist_ok=True)
                else:
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(mod, dst)
            # both shapes import from the staging dir: it is the parent
            # of a copied package dir and the holder of a copied file
            payload["py_modules"].append(os.path.dirname(dst))
        return payload

    @staticmethod
    def _pip_requirements(env: dict) -> list[str]:
        """Requirement strings in PIP syntax.  Conda dependencies
        translate: conda's single-``=`` version pins become pip ``==``
        pins; interpreter pins (``python=3.x``) are VALIDATED against
        the running interpreter (this deployment cannot materialize a
        different Python — no conda binary, no egress; see the README
        capability matrix) and fail staging loudly on mismatch rather
        than silently dropping."""
        import re
        import sys
        reqs = list(env.get("pip") or [])
        conda = env.get("conda")
        if isinstance(conda, dict):
            for d in conda.get("dependencies", ()):
                if not isinstance(d, str):
                    continue
                name = re.split(r"[=<>!~\[;\s]", d.strip(), 1)[0]
                if name == "python":
                    if not _python_pin_satisfied(d.strip()):
                        raise RuntimeEnvSetupError(
                            f"conda interpreter pin {d!r} does not "
                            f"match the running Python "
                            f"{'.'.join(map(str, sys.version_info[:3]))}"
                            " — this deployment provisions conda specs "
                            "through the pip wheelhouse and cannot "
                            "install a different interpreter (no conda "
                            "binary, no egress)")
                    continue
                # name=1.2 (conda) -> name==1.2 (pip); leave ==/>=/etc
                reqs.append(re.sub(r"(?<![=<>!~])=(?!=)", "==", d))
        return reqs

    def _provision_pip(self, env: dict, stage_dir: str,
                       payload: dict) -> None:
        """Install pip requirements OFFLINE from the configured local
        wheelhouse into ``<stage>/pip_pkgs`` (digest-keyed: a cache hit
        skips the install entirely) and put it on the worker path.
        Falls back to present-interpreter validation when no wheelhouse
        is configured (reference: ``python/ray/_private/runtime_env/``
        pip plugin; SURVEY.md §1 layer 10 — mount empty)."""
        import subprocess
        import sys

        from ..common.config import get_config
        reqs = self._pip_requirements(env)
        if not reqs:
            return
        wheelhouse = get_config().runtime_env_wheelhouse
        if not wheelhouse:
            self._check_requirements(env)
            return
        target = os.path.join(stage_dir, "pip_pkgs")
        if not os.path.isdir(target):
            tmp = target + ".tmp"
            shutil.rmtree(tmp, ignore_errors=True)
            proc = subprocess.run(
                [sys.executable, "-m", "pip", "install", "--no-index",
                 "--find-links", wheelhouse, "--target", tmp,
                 "--quiet", *reqs],
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                shutil.rmtree(tmp, ignore_errors=True)
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeEnvSetupError(
                    f"pip provisioning failed for {reqs!r} from "
                    f"wheelhouse {wheelhouse!r}: {tail[-800:]}")
            os.makedirs(stage_dir, exist_ok=True)
            os.rename(tmp, target)      # visible only when complete
            # Monotonic gauge bumped outside _lock on purpose: a lost
            # increment only undercounts a diagnostic, and taking _lock
            # here would hold it across slow pip subprocess cleanup.
            self.num_pip_installs += 1  # rtlint: disable=W7
        payload["py_modules"].append(target)

    def _check_requirements(self, env: dict) -> None:
        """Zero-egress pip/conda: requirements must already be present in
        the interpreter environment.  Checked against the DISTRIBUTION
        namespace first (pip requirements name distributions, and import
        names can differ: scikit-learn/sklearn, pillow/PIL), with an
        import-name probe as fallback."""
        import importlib.util
        import re
        from importlib import metadata
        reqs = list(env.get("pip") or [])
        conda = env.get("conda")
        if isinstance(conda, dict):
            reqs += [d for d in conda.get("dependencies", ())
                     if isinstance(d, str)]
        for req in reqs:
            name = re.split(r"[=<>!~\[;\s]", req.strip(), 1)[0]
            try:
                metadata.version(name)
                continue
            except metadata.PackageNotFoundError:
                pass
            try:
                found = importlib.util.find_spec(
                    name.replace("-", "_")) is not None
            except (ImportError, ValueError):
                found = False
            if not found:
                raise RuntimeEnvSetupError(
                    f"runtime_env requirement {req!r} is not installed "
                    "and this deployment has no package egress "
                    "(pip/conda provisioning is validation-only here)")

    def stats(self) -> dict:
        with self._lock:
            return {"num_staged": self.num_staged,
                    "num_cached": len(self._cache),
                    "num_failed": len(self._errors),
                    "num_pip_installs": self.num_pip_installs}


def apply_payload(payload: dict | None) -> None:
    """Worker-side: enter the staged environment (env vars, working dir,
    module paths) before executing any task."""
    if not payload:
        return
    import sys
    os.environ.update(payload.get("env_vars") or {})
    for p in payload.get("py_modules") or []:
        if p not in sys.path:
            sys.path.insert(0, p)
    wd = payload.get("working_dir")
    if wd:
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
