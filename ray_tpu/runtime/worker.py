"""Worker process: executes tasks shipped by the raylet.

Reference parity: the worker side of upstream's core worker —
``CoreWorker::ExecuteTask`` receiving ``PushTask`` RPCs, with an in-worker
API surface so user functions can call ``get/put/wait/.remote`` from inside
a task, async actors running on an event loop, and threaded actors with
bounded ``max_concurrency`` / concurrency groups
(``src/ray/core_worker/``, SURVEY.md §1 layer 7, §3.2 tail; mount empty).

Transport: one duplex ``multiprocessing`` connection to the owning raylet.
A dedicated READER thread owns ``conn.recv`` and routes frames: replies to
this worker's own requests go to a reply queue (API calls are serialized
by a lock, so exactly one is outstanding); work frames (exec, actor
lifecycle) go to the work queue the main thread drains.  This is what
lets concurrent actor calls block in ``ray.get`` independently — the
reference gets the same property from the core worker's dedicated IO
service thread.

Frames (tuples, first element is the kind):
  raylet -> worker: ("fn", fn_id, bytes), ("exec", task_id_bin, fn_id,
                    payload, trace_ctx, extern), ("get_reply*", ...),
                    ("wait_reply", payload), ("shutdown",)
  worker -> raylet: ("ready",), ("result", task_id_bin, [bytes, ...],
                    contained), ("error", task_id_bin, bytes),
                    ("get", [oid_bin, ...]), ("wait", ...),
                    ("put", oid_bin, bytes, contained),
                    ("submit", spec_bytes, fn_id, fn_bytes | None),
                    ("refs", [(delta, oid_bin), ...])
"""

from __future__ import annotations

import contextvars
import logging
import os
import queue
import sys
import threading

from ..common.ids import ObjectID, TaskID
from .object_ref import ObjectRef
from .serialization import RayTaskError, deserialize, serialize

# reply frame kinds the reader routes to the API reply queue
_REPLY_KINDS = frozenset({"get_reply", "get_reply_x", "wait_reply",
                          "kv_reply", "named_actor_reply",
                          "named_list_reply", "stream_wait_reply"})


def _format_all_stacks() -> str:
    """Every thread's current Python stack, named — what is this
    process doing RIGHT NOW."""
    import sys
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = [f"pid {os.getpid()}"]
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, ident)} ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out)


class ArgRef:
    """A task argument shipped as a store descriptor instead of a value:
    shm-resident args are read zero-copy from the worker's arena mapping
    (reference: plasma args are mmap views, not copies)."""

    __slots__ = ("desc",)

    def __init__(self, desc):
        self.desc = desc

    def __reduce__(self):
        return (ArgRef, (self.desc,))


class WorkerRefCounter:
    """This worker process's share of distributed refcounting: local
    ObjectRef construction/destruction queue here (``__del__``-safe,
    lock-free) and batches ship to the raylet as ``("refs", …)`` frames,
    where they fold against this worker's HOLDER entry in the head's
    ReferenceCounter.  A stashed borrowed ref therefore keeps its object
    alive after the lending task returns; worker death retires the whole
    holder (reference: per-worker ReferenceCounter + borrower protocol,
    SURVEY.md §1 layer 7; mount empty)."""

    def __init__(self):
        from collections import deque
        self._events: deque = deque()

    def incref(self, object_id) -> None:
        self._events.append((1, object_id))

    def decref(self, object_id) -> None:
        self._events.append((-1, object_id))

    def drain(self) -> list:
        out = []
        while True:
            try:
                delta, oid = self._events.popleft()
            except IndexError:
                return out
            out.append((delta, oid.binary()))


class WorkerApiContext:
    """The in-worker implementation of the public API (get/put/submit).

    Installed as the process-global runtime by ``worker_main``; the
    ``ray_tpu.api`` front end routes to it when running inside a worker.
    Thread-safe: concurrent actor calls share it (sends are serialized;
    request/reply API calls additionally hold ``_api_lock`` end-to-end,
    which also keeps get-ack order matched to the raylet's pin FIFO).
    The current task id is a context variable, so it is correct per
    thread AND per asyncio task."""

    is_driver = False

    def __init__(self, conn, arena_path: str | None = None):
        self._conn = conn
        self._task_var: contextvars.ContextVar = \
            contextvars.ContextVar("rt_task", default=None)
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._arena_path = arena_path
        self._arena = None          # lazily attached, read-only
        self._arena_lock = threading.Lock()
        self.ref_counter = WorkerRefCounter()
        self._send_lock = threading.Lock()
        self._api_lock = threading.RLock()
        self._flush_lock = threading.Lock()
        self._reply_q: queue.SimpleQueue = queue.SimpleQueue()
        # streaming-generator backpressure: highest consumer-acked item
        # per task (fed by the reader thread's stream_ack routing)
        self._stream_acks: dict[bytes, int] = {}
        self._stream_active: set[bytes] = set()
        self._stream_cancelled: set[bytes] = set()
        self._stream_cv = threading.Condition()
        # runtime-context identity (reference: ray.get_runtime_context)
        self.node_id_hex: str | None = None     # fed by "node_info"
        self.actor_id_bin: bytes | None = None  # set at actor_new

    # -- transport ----------------------------------------------------------
    def send(self, msg) -> None:
        with self._send_lock:
            self._conn.send(msg)

    def reader_loop(self, work_q: queue.SimpleQueue) -> None:
        """Owns ``conn.recv``: replies to our API calls go to the reply
        queue, work frames to the main loop's queue.  EOF poisons both."""
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                break
            if msg[0] in _REPLY_KINDS:
                self._reply_q.put(msg)
            elif msg[0] == "dump_stacks":
                # live stack sampling (upstream: the dashboard's py-spy
                # integration — SURVEY §5.1(c)): answered ON THE READER
                # THREAD so a worker wedged in user code (the exact
                # case you want to inspect) still replies
                try:
                    self.send(("stacks_reply", msg[1],
                               _format_all_stacks()))
                except Exception:   # noqa: BLE001 — diagnostics only;
                    # the reader must survive, but record the failure
                    logging.getLogger("ray_tpu.worker").debug(
                        "stack-dump reply failed", exc_info=True)
            elif msg[0] == "node_info":
                # which node hosts this worker (runtime-context
                # surface) — set from the reader so it is visible even
                # while the main thread executes a long task
                self.node_id_hex = msg[1]
            elif msg[0] == "stream_ack":
                # out-of-band: the main thread is inside the generator.
                # Only ACTIVE streams record (a late ack after
                # stream_done must not re-create the entry)
                with self._stream_cv:
                    if msg[1] in self._stream_active:
                        prev = self._stream_acks.get(msg[1], 0)
                        self._stream_acks[msg[1]] = max(prev, msg[2])
                        self._stream_cv.notify_all()
            elif msg[0] == "stream_cancel":
                with self._stream_cv:
                    # ACTIVE streams only (like stream_ack): a cancel
                    # racing past stream_done must not park a dead
                    # entry in the set forever
                    if msg[1] in self._stream_active:
                        self._stream_cancelled.add(msg[1])
                        self._stream_cv.notify_all()
            else:
                work_q.put(msg)
        work_q.put(None)
        self._reply_q.put(None)

    def flush_refs(self) -> None:
        """Ship queued local ref events to the raylet.  Drain and send
        hold one lock so concurrent actor-call threads cannot split a
        +/- pair across two frames that then hit the wire out of order
        (per-holder event order is the counter's correctness
        invariant)."""
        with self._flush_lock:
            events = self.ref_counter.drain()
            if events:
                self.send(("refs", events))

    def _materialize(self, desc, extern=None):
        """Resolve a descriptor: in-band value ("v"), in-band serialized
        value ("vb"), in-band serialized payload ("b"), a zero-copy
        arena read ("s"), or an extern-table indirection ("x" — plane
        mode ships plasma descriptors OUTSIDE the payload pickle so the
        node agent can resolve them against its local arena)."""
        kind = desc[0]
        if kind == "x":
            desc = extern[desc[1]]
            kind = desc[0]
        if kind == "v":
            return desc[1]
        if kind in ("b", "vb"):
            return deserialize(desc[1])
        if kind == "r":
            raise RuntimeError(
                "unresolved by-reference descriptor reached the worker "
                "(the node agent failed to rewrite it)")
        # ("s", offset, size): attach the arena once, read zero-copy
        if self._arena is None:
            with self._arena_lock:
                if self._arena is None:
                    from ..native import Arena
                    self._arena = Arena(self._arena_path)
        return deserialize(self._arena.view(desc[1], desc[2]))

    def _recv_reply(self, expected_kinds):
        if isinstance(expected_kinds, str):
            expected_kinds = (expected_kinds,)
        while True:
            msg = self._reply_q.get()
            if msg is None:
                raise ConnectionError("raylet connection lost")
            if msg[0] in expected_kinds:
                return msg
            # stale reply (an abandoned earlier call): drop it

    def stream_begin(self, task_id_bin: bytes) -> None:
        with self._stream_cv:
            self._stream_active.add(task_id_bin)
            self._stream_cancelled.discard(task_id_bin)

    def stream_wait_budget(self, task_id_bin: bytes, produced: int,
                           window: int) -> bool:
        """Generator backpressure: pause until the consumer has acked
        within ``window`` of what we produced.  A slow-but-alive
        consumer keeps memory bounded (every ack re-arms the clock);
        an ABANDONED stream normally cancels cooperatively
        (ObjectRefGenerator close/GC sends stream_cancel), and the
        10-minute no-progress cap catches ORPHANED streams whose
        consumer can never close them (a transferred generator whose
        carrier task died before delivery) — the producer then stops
        yielding instead of holding its worker forever.  Returns False
        when the producer should stop."""
        import time as _time
        deadline = _time.monotonic() + 600.0
        with self._stream_cv:
            last = self._stream_acks.get(task_id_bin, 0)
            while produced - self._stream_acks.get(task_id_bin, 0) \
                    >= window:
                if task_id_bin in self._stream_cancelled:
                    return "cancelled"
                acked = self._stream_acks.get(task_id_bin, 0)
                if acked > last:        # consumer alive: re-arm
                    last = acked
                    deadline = _time.monotonic() + 600.0
                if _time.monotonic() >= deadline:
                    return "stalled"    # orphaned: stop producing
                self._stream_cv.wait(1.0)
            return "cancelled" if task_id_bin in self._stream_cancelled \
                else "ok"

    def stream_done(self, task_id_bin: bytes) -> None:
        with self._stream_cv:
            self._stream_acks.pop(task_id_bin, None)
            self._stream_active.discard(task_id_bin)
            self._stream_cancelled.discard(task_id_bin)

    # -- task lifecycle (called by the exec paths) --------------------------
    def begin_task(self, task_id: TaskID):
        return self._task_var.set(task_id)

    def end_task(self, token=None):
        if token is not None:
            self._task_var.reset(token)
        else:
            self._task_var.set(None)

    @property
    def current_task_id(self) -> TaskID | None:
        return self._task_var.get()

    # -- API ----------------------------------------------------------------
    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        # the WHOLE request/reply/materialize/ack sequence holds the api
        # lock: the raylet releases get-reply pins on acks in FIFO order
        # per worker, so two threads' acks must not interleave
        with self._api_lock:
            self.send(("get", [r.binary() for r in refs], timeout))
            msg = self._recv_reply(("get_reply", "get_reply_x"))
            if msg[0] == "get_reply":
                status, descs = deserialize(msg[1])
            else:       # plane mode: descriptors ride outside the pickle
                status, descs = msg[1], msg[2]
            if status == "timeout":
                from .object_store import GetTimeoutError
                raise GetTimeoutError(
                    f"get timed out after {timeout}s inside worker")
            try:
                values = [self._materialize(d) for d in descs]
            finally:
                # ack releases the raylet/agent-side pins on this
                # reply's shm descriptors; sent only when any exist
                if any(d[0] == "s" for d in descs):
                    self.send(("get_ack",))
        for v in values:
            if isinstance(v, RayTaskError):
                raise v.cause if v.cause is not None else v
        return values

    def put(self, value) -> ObjectRef:
        task_id = self.current_task_id
        assert task_id is not None, "put outside a task"
        with self._put_lock:
            self._put_index += 1
            idx = self._put_index
        # the process-wide monotonic index keeps put ids unique across
        # concurrent calls (per-task indexes could collide after an
        # interleaving); ids still embed the creating task
        oid = ObjectID.for_put(task_id, idx)
        from .object_ref import serialize_collecting
        data, contained = serialize_collecting(value)
        self.flush_refs()
        self.send(("put", oid.binary(), data, contained))
        return ObjectRef(oid)

    def wait(self, refs, num_returns, timeout):
        """True ray.wait semantics: the raylet-side store partitions by
        actual readiness; partial (ready, not_ready) on timeout, no raise."""
        with self._api_lock:
            self.send(("wait", [r.binary() for r in refs], num_returns,
                       timeout))
            _, payload = self._recv_reply("wait_reply")
        ready_bins = set(deserialize(payload))
        ready = [r for r in refs if r.binary() in ready_bins]
        not_ready = [r for r in refs if r.binary() not in ready_bins]
        return ready, not_ready

    def submit_spec(self, spec, fn_id: str, fn_bytes: bytes | None):
        from .object_ref import mark_transferred, transfer_generators
        self.flush_refs()
        with transfer_generators() as gens:
            payload = serialize(spec)
        self.send(("submit", payload, fn_id, fn_bytes))
        mark_transferred(gens)      # bytes shipped: consumption moved

    # streaming-generator CONSUMPTION from inside a worker: waits and
    # acks proxy through the raylet, so ObjectRefGenerators chain
    # through tasks (a task can consume another task's or actor's
    # stream — reference: generators are first-class task arguments)
    def stream_wait(self, task_id, index, timeout=None):
        # bounded server-side waits looped client-side (the
        # ClientRuntime pattern): the api lock releases between polls,
        # so one call consuming a slow stream cannot head-of-line-block
        # every other concurrent call's get/put/wait on this worker
        import time as _time
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        while True:
            # 15s server-side bound: long enough that the raylet's
            # blocked-worker dance (recall/add_back/re-debit) stays
            # rare churn, short enough that concurrent calls on this
            # worker wait a bounded time for the api lock
            if deadline is None:
                step = 15.0
            else:
                step = min(15.0, max(0.0, deadline - _time.monotonic()))
            with self._api_lock:
                self.send(("stream_wait", task_id.binary(), index, step))
                reply = self._recv_reply("stream_wait_reply")
            sealed, done, err_bytes = reply[1], reply[2], reply[3]
            known = reply[4] if len(reply) > 4 else True
            if sealed > index or done or not known or \
                    (deadline is not None
                     and _time.monotonic() >= deadline):
                return (sealed, done,
                        deserialize(err_bytes) if err_bytes else None,
                        known)

    def stream_ack(self, task_id, consumed) -> None:
        self.send(("stream_ack_up", task_id.binary(), consumed))

    def stream_close(self, task_id, consumed) -> None:
        self.send(("stream_close_up", task_id.binary(), consumed))

    def kv_op(self, op: str, key: bytes, value: bytes | None = None,
              namespace: str = "", overwrite: bool = True):
        """GCS KV access from inside a task (internal_kv parity)."""
        with self._api_lock:
            self.send(("kv", op, key, value, namespace, overwrite))
            reply = self._recv_reply("kv_reply")
        if reply[2] is not None:
            raise RuntimeError(f"internal_kv {op} failed: {reply[2]}")
        return reply[1]

    # -- actor API (frames handled by the driver's ActorManager) ------------
    def create_actor(self, actor_id, cls_id: str, cls_bytes: bytes | None,
                     args, kwargs, max_restarts: int, max_task_retries: int,
                     name: str | None, resources=None, strategy=None,
                     runtime_env=None, concurrency: dict | None = None,
                     namespace: str = "", lifetime: str | None = None):
        self.flush_refs()
        self.send(("actor_create", actor_id.binary(), cls_id,
                   cls_bytes, serialize(
                       (args, kwargs, max_restarts, max_task_retries,
                        name, resources, strategy, runtime_env,
                        concurrency, namespace, lifetime))))

    # -- placement groups (frames handled by the raylet) --------------------
    def create_placement_group(self, pg_id, bundles, strategy_name: str,
                               name: str | None):
        self.send(("pg_create", pg_id.binary(),
                   serialize((bundles, strategy_name, name))))

    def remove_placement_group(self, pg_id):
        self.send(("pg_remove", pg_id.binary()))

    def submit_actor_call(self, actor_id, task_id, method: str, args,
                          kwargs, num_returns: int,
                          trace_ctx: tuple | None = None,
                          concurrency_group: str | None = None):
        from .object_ref import mark_transferred, transfer_generators
        self.flush_refs()
        with transfer_generators() as gens:
            payload = serialize((args, kwargs, num_returns, trace_ctx,
                                 concurrency_group))
        self.send(("actor_submit", actor_id.binary(),
                   task_id.binary(), method, payload))
        mark_transferred(gens)

    def kill_actor(self, actor_id, no_restart: bool = True):
        self.send(("actor_kill", actor_id.binary(), no_restart))

    def get_actor_id_by_name(self, name: str, namespace: str = ""):
        with self._api_lock:
            self.send(("named_actor", name, namespace))
            return self._recv_reply("named_actor_reply")[1]

    def list_named_actors_via_head(self, namespace):
        """Named-actor listing from inside a task/actor (None = every
        namespace)."""
        with self._api_lock:
            self.send(("named_list", namespace))
            return self._recv_reply("named_list_reply")[1]


class _ActorExecutor:
    """Runs one actor's method calls under its concurrency model.

    Reference parity: async actors run coroutine methods on a dedicated
    event loop (default ``max_concurrency`` 1000); threaded actors run
    up to ``max_concurrency`` calls on a pool; ``concurrency_groups``
    bound named groups independently, with the unnamed remainder on the
    default group (core worker's ``ConcurrencyGroupManager`` /
    ``FiberStateManager`` — SURVEY.md §1 layer 7; mount empty).
    ``max_concurrency == 1`` executes inline on the main loop thread,
    preserving the strict FIFO the reference gives plain actors."""

    def __init__(self, ctx: WorkerApiContext, instance,
                 concurrency: dict | None):
        import inspect
        self._ctx = ctx
        self.instance = instance
        conc = concurrency or {}
        self._is_async = any(
            inspect.iscoroutinefunction(m)
            or inspect.isasyncgenfunction(m)
            for _n, m in inspect.getmembers(type(instance))
            if callable(m))
        default = 1000 if self._is_async else 1
        self.max_concurrency = int(conc.get("max_concurrency") or default)
        self._groups: dict[str, object] = {}
        self._loop = None
        self._loop_thread = None
        self._sem = None
        group_sizes = dict(conc.get("concurrency_groups") or {})
        if self._is_async:
            import asyncio
            self._loop = asyncio.new_event_loop()
            self._loop_thread = threading.Thread(
                target=self._loop.run_forever, daemon=True,
                name="actor-async-loop")
            self._loop_thread.start()
            self._sem = {
                None: asyncio.Semaphore(self.max_concurrency)}
            for gname, n in group_sizes.items():
                self._sem[gname] = asyncio.Semaphore(int(n))
        elif self.max_concurrency > 1 or group_sizes:
            from concurrent.futures import ThreadPoolExecutor
            self._groups[None] = ThreadPoolExecutor(
                max_workers=self.max_concurrency,
                thread_name_prefix="actor-call")
            for gname, n in group_sizes.items():
                self._groups[gname] = ThreadPoolExecutor(
                    max_workers=int(n),
                    thread_name_prefix=f"actor-{gname}")

    @property
    def inline(self) -> bool:
        return self._loop is None and not self._groups

    def dispatch(self, run, group: str | None) -> None:
        """Run ``run()`` (a fully-bound call closure) under the model."""
        if self._loop is not None:
            import asyncio
            sem = self._sem.get(group) or self._sem[None]

            async def guarded():
                async with sem:
                    await run()
            asyncio.run_coroutine_threadsafe(guarded(), self._loop)
            return
        pool = self._groups.get(group) or self._groups.get(None)
        if pool is None:
            run()
        else:
            pool.submit(run)

    def shutdown(self) -> None:
        for pool in self._groups.values():
            pool.shutdown(wait=True)
        if self._loop is not None:
            # drain ON the loop: run_coroutine_threadsafe callbacks are
            # FIFO, so every previously dispatched call has created its
            # task by the time drain() runs — counting from this thread
            # instead would race task creation (and iterate the task
            # WeakSet unsafely from outside the loop)
            import asyncio

            async def drain():
                while True:
                    others = [t for t in asyncio.all_tasks()
                              if t is not asyncio.current_task()]
                    if not others:
                        return
                    await asyncio.gather(*others,
                                         return_exceptions=True)
            fut = asyncio.run_coroutine_threadsafe(drain(), self._loop)
            try:
                fut.result(timeout=10.0)
            except Exception:   # noqa: BLE001 — wedge: stop anyway
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=5.0)


class _CallScope:
    """Shared per-call scaffolding: task context + trace span on entry;
    span exit, task reset, error frame, and ref flush on the way out."""

    def __init__(self, ctx: WorkerApiContext, task_id_bin: bytes,
                 method: str, trace_ctx):
        self._ctx = ctx
        self._tid = task_id_bin
        self._method = method
        self._trace = trace_ctx
        self._scope = None
        self._token = None

    def __enter__(self):
        self._token = self._ctx.begin_task(TaskID(self._tid))
        if self._trace is not None:
            from ..util.tracing import span_scope
            self._scope = span_scope(self._trace[0],
                                     TaskID(self._tid).hex())
            self._scope.__enter__()
        return self

    def __exit__(self, exc_type, exc, _tb):
        if exc is not None:
            self._ctx.send(("actor_error", self._tid, serialize(
                RayTaskError.from_exception(self._method, exc))))
        if self._scope is not None:
            self._scope.__exit__(None, None, None)
        self._ctx.end_task(self._token)
        try:
            self._ctx.flush_refs()
        except (OSError, BrokenPipeError):
            pass
        return True         # error already shipped as a frame


def _stream_results(ctx: WorkerApiContext, task_id_bin: bytes, out,
                    result_kind: str) -> None:
    """Drive a generator's items through the streaming protocol: each
    yield seals incrementally, the consumer's acks slide the
    backpressure window, and the terminal result frame (``result`` for
    tasks, ``actor_result`` for actor calls) closes the bookkeeping."""
    from ..common.config import get_config
    from .object_ref import serialize_collecting
    window = max(get_config().streaming_backpressure_items, 1)
    ctx.stream_begin(task_id_bin)
    idx = 0
    verdict = "ok"
    try:
        for item in out:
            idx += 1
            data, inner = serialize_collecting(item)
            ctx.send(("stream_item", task_id_bin, idx, data, inner))
            item = data = inner = None
            verdict = ctx.stream_wait_budget(task_id_bin, idx, window)
            if verdict != "ok":
                break   # consumer closed the stream / orphaned
    finally:
        if hasattr(out, "close"):
            out.close()     # GeneratorExit into user code
        ctx.stream_done(task_id_bin)
    # a STALLED end is distinguishable: the head finishes the stream
    # with an error + tears it down, so a slow-but-alive consumer gets
    # a loud failure instead of a silently truncated clean end
    ctx.send(("stream_end", task_id_bin, idx,
              verdict == "stalled"))
    ctx.send((result_kind, task_id_bin, [], []))


def _run_actor_call(ctx: WorkerApiContext, executor: _ActorExecutor,
                    task_id_bin: bytes, method: str, args, kwargs,
                    num_returns: int, trace_ctx) -> None:
    """Execute one actor method call and ship its result — runs inline
    or on a pool thread."""
    with _CallScope(ctx, task_id_bin, method, trace_ctx):
        out = getattr(executor.instance, method)(*args, **kwargs)
        if hasattr(out, "__await__"):
            raise RuntimeError("coroutine escaped the async path")
        if num_returns == -1:
            _stream_results(ctx, task_id_bin, out, "actor_result")
        else:
            _send_call_results(ctx, task_id_bin, method, out,
                               num_returns)


async def _run_actor_call_async(ctx, executor, task_id_bin, method,
                                args, kwargs, num_returns,
                                trace_ctx) -> None:
    with _CallScope(ctx, task_id_bin, method, trace_ctx):
        out = getattr(executor.instance, method)(*args, **kwargs)
        if hasattr(out, "__await__"):
            out = await out
        if num_returns == -1:
            if hasattr(out, "__aiter__"):
                # async generator: collect through the same protocol
                # with awaited iteration
                await _stream_results_async(ctx, task_id_bin, out)
            else:
                # sync generator on the LOOP thread: its backpressure
                # waits block — run it on the executor so concurrent
                # async calls keep serving
                import asyncio
                await asyncio.get_running_loop().run_in_executor(
                    None, _stream_results, ctx, task_id_bin, out,
                    "actor_result")
        else:
            _send_call_results(ctx, task_id_bin, method, out,
                               num_returns)


async def _stream_results_async(ctx, task_id_bin: bytes, out) -> None:
    import asyncio

    from ..common.config import get_config
    from .object_ref import serialize_collecting
    window = max(get_config().streaming_backpressure_items, 1)
    loop = asyncio.get_running_loop()
    ctx.stream_begin(task_id_bin)
    idx = 0
    verdict = "ok"
    try:
        async for item in out:
            idx += 1
            data, inner = serialize_collecting(item)
            ctx.send(("stream_item", task_id_bin, idx, data, inner))
            item = data = inner = None
            # backpressure wait off the loop thread (it blocks)
            verdict = await loop.run_in_executor(
                None, ctx.stream_wait_budget, task_id_bin, idx, window)
            if verdict != "ok":
                break
    finally:
        try:
            await out.aclose()      # user finally/cleanup runs NOW,
        except Exception:           # not at GC finalization
            pass
        ctx.stream_done(task_id_bin)
    ctx.send(("stream_end", task_id_bin, idx,
              verdict == "stalled"))
    ctx.send(("actor_result", task_id_bin, [], []))


def _send_call_results(ctx, task_id_bin, method, out,
                       num_returns: int) -> None:
    from .object_ref import serialize_collecting
    if num_returns == 1:
        results = [out]
    elif num_returns == 0:
        results = []
    else:
        results = list(out)
        if len(results) != num_returns:
            raise ValueError(
                f"actor method {method} declared num_returns="
                f"{num_returns} but returned {len(results)} values")
    payloads, contained = [], []
    for r in results:
        data, inner = serialize_collecting(r)
        payloads.append(data)
        contained.append(inner)
    ctx.send(("actor_result", task_id_bin, payloads, contained))


def worker_main(conn, worker_index: int,
                arena_path: str | None = None,
                runtime_env_payload: dict | None = None) -> None:
    """Entry point of a spawned worker process."""
    # workers never own the TPU: the device data plane belongs to the
    # raylet/driver process; user task code that imports jax gets CPU.
    # FORCED, not setdefault — the ambient environment may already pin
    # JAX_PLATFORMS to the TPU platform (single chip, owned elsewhere),
    # and a worker trying to claim it fails or contends
    os.environ["JAX_PLATFORMS"] = "cpu"
    # enter the staged runtime environment BEFORE any user code runs
    from .runtime_env import apply_payload
    apply_payload(runtime_env_payload)

    from .. import api

    ctx = WorkerApiContext(conn, arena_path)
    api._set_runtime(ctx)
    from .object_ref import install_counter, serialize_collecting
    install_counter(ctx.ref_counter)
    fn_table: dict[str, object] = {}
    executor: _ActorExecutor | None = None   # dedicated worker: one actor
    actor_id_bin = None
    work_q: queue.SimpleQueue = queue.SimpleQueue()
    threading.Thread(target=ctx.reader_loop, args=(work_q,),
                     daemon=True, name="rt-worker-reader").start()
    ctx.send(("ready",))

    while True:
        msg = work_q.get()
        if msg is None:
            break
        kind = msg[0]
        if kind == "fn":
            fn_table[msg[1]] = deserialize(msg[2])
        elif kind == "exec":
            if len(msg) == 6:
                _, task_id_bin, fn_id, payload, trace_ctx, extern = msg
            else:           # pre-plane frame shape
                _, task_id_bin, fn_id, payload, trace_ctx = msg
                extern = None
            args, kwargs, num_returns = deserialize(payload)
            args = tuple(ctx._materialize(a.desc, extern)
                         if isinstance(a, ArgRef) else a for a in args)
            fn = fn_table[fn_id]
            name = getattr(fn, "__qualname__", str(fn))
            token = ctx.begin_task(TaskID(task_id_bin))
            if trace_ctx is not None:
                # this task's span becomes the ambient scope, so specs
                # it submits inherit (trace_id, THIS span) as context
                from ..util.tracing import span_scope
                _scope = span_scope(trace_ctx[0], TaskID(task_id_bin).hex())
                _scope.__enter__()
            else:
                _scope = None
            try:
                out = fn(*args, **kwargs)
                if num_returns == -1:
                    # streaming generator: each yielded item seals
                    # incrementally; the consumer's acks drive
                    # backpressure (reference: streaming generator
                    # protocol, num_returns="streaming")
                    _stream_results(ctx, task_id_bin, out, "result")
                else:
                    if num_returns == 1:
                        results = [out]
                    elif num_returns == 0:
                        results = []
                    else:
                        results = list(out)
                        if len(results) != num_returns:
                            raise ValueError(
                                f"task {name} declared num_returns="
                                f"{num_returns} but returned "
                                f"{len(results)} values")
                    payloads, contained = [], []
                    for r in results:
                        data, inner = serialize_collecting(r)
                        payloads.append(data)
                        contained.append(inner)
                    ctx.send(("result", task_id_bin, payloads,
                              contained))
            except BaseException as e:  # noqa: BLE001 — any task failure
                err = RayTaskError.from_exception(name, e)
                try:
                    ctx.send(("error", task_id_bin, serialize(err)))
                except Exception:
                    ctx.send(("error", task_id_bin, serialize(
                        RayTaskError(name, err.tb, None))))
            finally:
                if _scope is not None:
                    _scope.__exit__(None, None, None)
                ctx.end_task(token)
                # task locals must die NOW, not when the next exec
                # overwrites these loop variables — their ObjectRefs'
                # decrefs ride the flush below ("r" is the serialization
                # loop variable, still bound to the last result)
                args = kwargs = out = results = payloads = r = None
        elif kind == "actor_new":
            _, actor_id_bin, cls_id, payload = msg
            ctx.actor_id_bin = actor_id_bin
            unpacked = deserialize(payload)
            if len(unpacked) == 3:
                args, kwargs, concurrency = unpacked
            else:           # pre-concurrency frame shape
                args, kwargs = unpacked
                concurrency = None
            cls = fn_table[cls_id]
            token = ctx.begin_task(TaskID.deterministic(actor_id_bin,
                                                        _nil_actor()))
            try:
                instance = cls(*args, **kwargs)
                executor = _ActorExecutor(ctx, instance, concurrency)
                ctx.send(("actor_ready", actor_id_bin))
            except BaseException as e:  # noqa: BLE001
                ctx.send(("actor_init_error", actor_id_bin, serialize(
                    RayTaskError.from_exception(
                        getattr(cls, "__name__", "actor") + ".__init__",
                        e))))
            finally:
                ctx.end_task(token)
                args = kwargs = None
        elif kind == "actor_call":
            _, task_id_bin, method, payload = msg
            unpacked = deserialize(payload)
            if len(unpacked) == 5:
                args, kwargs, num_returns, trace_ctx, group = unpacked
            else:           # pre-concurrency frame shape
                args, kwargs, num_returns, trace_ctx = unpacked
                group = None
            if method == "__ray_terminate__":
                # graceful stop: let in-flight concurrent calls finish
                if executor is not None:
                    executor.shutdown()
                ctx.send(("actor_exit", actor_id_bin))
                ctx.send(("actor_result", task_id_bin,
                          [serialize(None)], [[]]))
                break
            if executor is None:
                ctx.send(("actor_error", task_id_bin, serialize(
                    RayTaskError(method, "actor instance missing"))))
                args = kwargs = None
                continue
            if executor._loop is not None:
                coro_args = (ctx, executor, task_id_bin, method, args,
                             kwargs, num_returns, trace_ctx)
                executor.dispatch(
                    lambda a=coro_args: _run_actor_call_async(*a), group)
            elif executor.inline:
                _run_actor_call(ctx, executor, task_id_bin, method,
                                args, kwargs, num_returns, trace_ctx)
            else:
                call_args = (ctx, executor, task_id_bin, method, args,
                             kwargs, num_returns, trace_ctx)
                executor.dispatch(
                    lambda a=call_args: _run_actor_call(*a), group)
            args = kwargs = None
        elif kind == "shutdown":
            if executor is not None:
                executor.shutdown()
            break
        # ship ref events born while handling this frame (task locals
        # died, results built refs) — per-holder order rides the pipe
        try:
            ctx.flush_refs()
        except (OSError, BrokenPipeError):
            break
    sys.exit(0)


def _nil_actor():
    from ..common.ids import ActorID, JobID
    return ActorID.nil_for_job(JobID.from_int(0))
