"""Worker process: executes tasks shipped by the raylet.

Reference parity: the worker side of upstream's core worker —
``CoreWorker::ExecuteTask`` receiving ``PushTask`` RPCs, with an in-worker
API surface so user functions can call ``get/put/wait/.remote`` from inside
a task (``src/ray/core_worker/``, SURVEY.md §3.2 tail; mount empty).

Transport: one duplex ``multiprocessing`` connection to the owning raylet.
The worker is single-threaded and synchronous: while it executes a task the
only frames it can receive are replies to its own requests, so plain
send/recv pairs are race-free without correlation ids.

Frames (tuples, first element is the kind):
  raylet -> worker: ("fn", fn_id, bytes), ("exec", task_id_bin, fn_id,
                    payload, trace_ctx), ("get_reply", payload),
                    ("wait_reply", payload), ("shutdown",)
  worker -> raylet: ("ready",), ("result", task_id_bin, [bytes, ...]),
                    ("error", task_id_bin, bytes), ("get", [oid_bin, ...]),
                    ("wait", [oid_bin, ...], num_returns, timeout),
                    ("put", oid_bin, bytes), ("submit", spec_bytes,
                    fn_id, fn_bytes | None)
"""

from __future__ import annotations

import os
import sys

from ..common.ids import ObjectID, TaskID
from .object_ref import ObjectRef
from .serialization import RayTaskError, deserialize, serialize


class ArgRef:
    """A task argument shipped as a store descriptor instead of a value:
    shm-resident args are read zero-copy from the worker's arena mapping
    (reference: plasma args are mmap views, not copies)."""

    __slots__ = ("desc",)

    def __init__(self, desc):
        self.desc = desc

    def __reduce__(self):
        return (ArgRef, (self.desc,))


class WorkerRefCounter:
    """This worker process's share of distributed refcounting: local
    ObjectRef construction/destruction queue here (``__del__``-safe,
    lock-free) and batches ship to the raylet as ``("refs", …)`` frames,
    where they fold against this worker's HOLDER entry in the head's
    ReferenceCounter.  A stashed borrowed ref therefore keeps its object
    alive after the lending task returns; worker death retires the whole
    holder (reference: per-worker ReferenceCounter + borrower protocol,
    SURVEY.md §1 layer 7; mount empty)."""

    def __init__(self):
        from collections import deque
        self._events: deque = deque()

    def incref(self, object_id) -> None:
        self._events.append((1, object_id))

    def decref(self, object_id) -> None:
        self._events.append((-1, object_id))

    def drain(self) -> list:
        out = []
        while True:
            try:
                delta, oid = self._events.popleft()
            except IndexError:
                return out
            out.append((delta, oid.binary()))


class WorkerApiContext:
    """The in-worker implementation of the public API (get/put/submit).

    Installed as the process-global runtime by ``worker_main``; the
    ``ray_tpu.api`` front end routes to it when running inside a worker.
    """

    is_driver = False

    def __init__(self, conn, arena_path: str | None = None):
        self._conn = conn
        self._task_id: TaskID | None = None
        self._put_index = 0
        self._arena_path = arena_path
        self._arena = None          # lazily attached, read-only
        self.ref_counter = WorkerRefCounter()
        # frames that arrived while this worker was waiting for a reply
        # (pipelined actor calls land mid-get); the main loop drains them
        # in order after the current task finishes
        from collections import deque
        self.pending_frames = deque()

    def flush_refs(self) -> None:
        """Ship queued local ref events to the raylet (called at frame
        boundaries; FIFO on the pipe keeps per-holder event order)."""
        events = self.ref_counter.drain()
        if events:
            self._conn.send(("refs", events))

    def _materialize(self, desc, extern=None):
        """Resolve a descriptor: in-band value ("v"), in-band serialized
        value ("vb"), in-band serialized payload ("b"), a zero-copy
        arena read ("s"), or an extern-table indirection ("x" — plane
        mode ships plasma descriptors OUTSIDE the payload pickle so the
        node agent can resolve them against its local arena)."""
        kind = desc[0]
        if kind == "x":
            desc = extern[desc[1]]
            kind = desc[0]
        if kind == "v":
            return desc[1]
        if kind in ("b", "vb"):
            return deserialize(desc[1])
        if kind == "r":
            raise RuntimeError(
                "unresolved by-reference descriptor reached the worker "
                "(the node agent failed to rewrite it)")
        # ("s", offset, size): attach the arena once, read zero-copy
        if self._arena is None:
            from ..native import Arena
            self._arena = Arena(self._arena_path)
        return deserialize(self._arena.view(desc[1], desc[2]))

    def _recv_reply(self, expected_kinds):
        if isinstance(expected_kinds, str):
            expected_kinds = (expected_kinds,)
        while True:
            msg = self._conn.recv()
            if msg[0] in expected_kinds:
                return msg
            self.pending_frames.append(msg)

    # -- task lifecycle (called by the exec loop) ---------------------------
    def begin_task(self, task_id: TaskID):
        self._task_id = task_id
        self._put_index = 0

    def end_task(self):
        self._task_id = None

    @property
    def current_task_id(self) -> TaskID | None:
        return self._task_id

    # -- API ----------------------------------------------------------------
    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        self._conn.send(("get", [r.binary() for r in refs], timeout))
        msg = self._recv_reply(("get_reply", "get_reply_x"))
        if msg[0] == "get_reply":
            status, descs = deserialize(msg[1])
        else:       # plane mode: descriptors ride outside the pickle
            status, descs = msg[1], msg[2]
        if status == "timeout":
            from .object_store import GetTimeoutError
            raise GetTimeoutError(
                f"get timed out after {timeout}s inside worker")
        try:
            values = [self._materialize(d) for d in descs]
        finally:
            # ack releases the raylet/agent-side pins on this reply's
            # shm descriptors; sent only when the reply carried any
            if any(d[0] == "s" for d in descs):
                self._conn.send(("get_ack",))
        for v in values:
            if isinstance(v, RayTaskError):
                raise v.cause if v.cause is not None else v
        return values

    def put(self, value) -> ObjectRef:
        assert self._task_id is not None, "put outside a task"
        self._put_index += 1
        oid = ObjectID.for_put(self._task_id, self._put_index)
        from .object_ref import serialize_collecting
        data, contained = serialize_collecting(value)
        self.flush_refs()
        self._conn.send(("put", oid.binary(), data, contained))
        return ObjectRef(oid)

    def wait(self, refs, num_returns, timeout):
        """True ray.wait semantics: the raylet-side store partitions by
        actual readiness; partial (ready, not_ready) on timeout, no raise."""
        self._conn.send(("wait", [r.binary() for r in refs], num_returns,
                         timeout))
        _, payload = self._recv_reply("wait_reply")
        ready_bins = set(deserialize(payload))
        ready = [r for r in refs if r.binary() in ready_bins]
        not_ready = [r for r in refs if r.binary() not in ready_bins]
        return ready, not_ready

    def submit_spec(self, spec, fn_id: str, fn_bytes: bytes | None):
        self._conn.send(("submit", serialize(spec), fn_id, fn_bytes))

    def kv_op(self, op: str, key: bytes, value: bytes | None = None,
              namespace: str = "", overwrite: bool = True):
        """GCS KV access from inside a task (internal_kv parity)."""
        self._conn.send(("kv", op, key, value, namespace, overwrite))
        reply = self._recv_reply("kv_reply")
        if reply[2] is not None:
            raise RuntimeError(f"internal_kv {op} failed: {reply[2]}")
        return reply[1]

    # -- actor API (frames handled by the driver's ActorManager) ------------
    def create_actor(self, actor_id, cls_id: str, cls_bytes: bytes | None,
                     args, kwargs, max_restarts: int, max_task_retries: int,
                     name: str | None, resources=None, strategy=None,
                     runtime_env=None):
        self._conn.send(("actor_create", actor_id.binary(), cls_id,
                         cls_bytes, serialize(
                             (args, kwargs, max_restarts, max_task_retries,
                              name, resources, strategy, runtime_env))))

    # -- placement groups (frames handled by the raylet) --------------------
    def create_placement_group(self, pg_id, bundles, strategy_name: str,
                               name: str | None):
        self._conn.send(("pg_create", pg_id.binary(),
                         serialize((bundles, strategy_name, name))))

    def remove_placement_group(self, pg_id):
        self._conn.send(("pg_remove", pg_id.binary()))

    def submit_actor_call(self, actor_id, task_id, method: str, args,
                          kwargs, num_returns: int,
                          trace_ctx: tuple | None = None):
        self._conn.send(("actor_submit", actor_id.binary(),
                         task_id.binary(), method,
                         serialize((args, kwargs, num_returns,
                                    trace_ctx))))

    def kill_actor(self, actor_id, no_restart: bool = True):
        self._conn.send(("actor_kill", actor_id.binary(), no_restart))

    def get_actor_id_by_name(self, name: str):
        self._conn.send(("named_actor", name))
        return self._recv_reply("named_actor_reply")[1]


def worker_main(conn, worker_index: int,
                arena_path: str | None = None,
                runtime_env_payload: dict | None = None) -> None:
    """Entry point of a spawned worker process."""
    # workers never own the TPU: the device data plane belongs to the
    # raylet/driver process; user task code that imports jax gets CPU.
    # FORCED, not setdefault — the ambient environment may already pin
    # JAX_PLATFORMS to the TPU platform (single chip, owned elsewhere),
    # and a worker trying to claim it fails or contends
    os.environ["JAX_PLATFORMS"] = "cpu"
    # enter the staged runtime environment BEFORE any user code runs
    from .runtime_env import apply_payload
    apply_payload(runtime_env_payload)

    from .. import api

    ctx = WorkerApiContext(conn, arena_path)
    api._set_runtime(ctx)
    from .object_ref import install_counter, serialize_collecting
    install_counter(ctx.ref_counter)
    fn_table: dict[str, object] = {}
    actor_instance = None            # dedicated worker: one actor
    actor_id_bin = None
    conn.send(("ready",))

    while True:
        if ctx.pending_frames:
            msg = ctx.pending_frames.popleft()
        else:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
        kind = msg[0]
        if kind == "fn":
            fn_table[msg[1]] = deserialize(msg[2])
        elif kind == "exec":
            if len(msg) == 6:
                _, task_id_bin, fn_id, payload, trace_ctx, extern = msg
            else:           # pre-plane frame shape
                _, task_id_bin, fn_id, payload, trace_ctx = msg
                extern = None
            args, kwargs, num_returns = deserialize(payload)
            args = tuple(ctx._materialize(a.desc, extern)
                         if isinstance(a, ArgRef) else a for a in args)
            fn = fn_table[fn_id]
            name = getattr(fn, "__qualname__", str(fn))
            ctx.begin_task(TaskID(task_id_bin))
            if trace_ctx is not None:
                # this task's span becomes the ambient scope, so specs
                # it submits inherit (trace_id, THIS span) as context
                from ..util.tracing import span_scope
                _scope = span_scope(trace_ctx[0], TaskID(task_id_bin).hex())
                _scope.__enter__()
            else:
                _scope = None
            try:
                out = fn(*args, **kwargs)
                if num_returns == 1:
                    results = [out]
                elif num_returns == 0:
                    results = []
                else:
                    results = list(out)
                    if len(results) != num_returns:
                        raise ValueError(
                            f"task {name} declared num_returns="
                            f"{num_returns} but returned {len(results)} "
                            "values")
                payloads, contained = [], []
                for r in results:
                    data, inner = serialize_collecting(r)
                    payloads.append(data)
                    contained.append(inner)
                conn.send(("result", task_id_bin, payloads, contained))
            except BaseException as e:  # noqa: BLE001 — any task failure
                err = RayTaskError.from_exception(name, e)
                try:
                    conn.send(("error", task_id_bin, serialize(err)))
                except Exception:
                    conn.send(("error", task_id_bin, serialize(
                        RayTaskError(name, err.tb, None))))
            finally:
                if _scope is not None:
                    _scope.__exit__(None, None, None)
                ctx.end_task()
                # task locals must die NOW, not when the next exec
                # overwrites these loop variables — their ObjectRefs'
                # decrefs ride the flush below ("r" is the serialization
                # loop variable, still bound to the last result)
                args = kwargs = out = results = payloads = r = None
        elif kind == "actor_new":
            _, actor_id_bin, cls_id, payload = msg
            args, kwargs = deserialize(payload)
            cls = fn_table[cls_id]
            ctx.begin_task(TaskID.deterministic(actor_id_bin,
                                                _nil_actor()))
            try:
                actor_instance = cls(*args, **kwargs)
                conn.send(("actor_ready", actor_id_bin))
            except BaseException as e:  # noqa: BLE001
                conn.send(("actor_init_error", actor_id_bin, serialize(
                    RayTaskError.from_exception(
                        getattr(cls, "__name__", "actor") + ".__init__",
                        e))))
            finally:
                ctx.end_task()
        elif kind == "actor_call":
            _, task_id_bin, method, payload = msg
            args, kwargs, num_returns, trace_ctx = deserialize(payload)
            if method == "__ray_terminate__":
                conn.send(("actor_exit", actor_id_bin))
                conn.send(("actor_result", task_id_bin, [serialize(None)]))
                break
            ctx.begin_task(TaskID(task_id_bin))
            if trace_ctx is not None:
                # tasks the actor method submits link under this call
                from ..util.tracing import span_scope
                _scope = span_scope(trace_ctx[0], TaskID(task_id_bin).hex())
                _scope.__enter__()
            else:
                _scope = None
            try:
                bound = getattr(actor_instance, method)
                out = bound(*args, **kwargs)
                if num_returns == 1:
                    results = [out]
                elif num_returns == 0:
                    results = []
                else:
                    results = list(out)
                    if len(results) != num_returns:
                        raise ValueError(
                            f"actor method {method} declared num_returns="
                            f"{num_returns} but returned {len(results)} "
                            "values")
                payloads, contained = [], []
                for r in results:
                    data, inner = serialize_collecting(r)
                    payloads.append(data)
                    contained.append(inner)
                conn.send(("actor_result", task_id_bin, payloads,
                           contained))
            except BaseException as e:  # noqa: BLE001
                conn.send(("actor_error", task_id_bin, serialize(
                    RayTaskError.from_exception(method, e))))
            finally:
                if _scope is not None:
                    _scope.__exit__(None, None, None)
                ctx.end_task()
                # call locals die now (see the exec branch)
                args = kwargs = out = results = payloads = r = None
        elif kind == "shutdown":
            break
        # ship ref events born while handling this frame (task locals
        # died, results built refs) — per-holder order rides the pipe
        try:
            ctx.flush_refs()
        except (OSError, BrokenPipeError):
            break
    sys.exit(0)


def _nil_actor():
    from ..common.ids import ActorID, JobID
    return ActorID.nil_for_job(JobID.from_int(0))
