"""Object store: in-band values + shared-memory arena + spill/restore.

Reference parity: upstream splits objects between the core worker's
in-process memory store (small/in-band) and the plasma shared-memory store
(large, zero-copy mmap reads, spill to external storage past a threshold)
— ``src/ray/core_worker/store_provider/memory_store/``,
``src/ray/object_manager/plasma/``, ``LocalObjectManager`` spill
(SURVEY.md §1 layers 6-7; mount empty).

Routing: serialized payloads larger than ``max_direct_call_object_size``
live in the native arena (``ray_tpu/native/arena.cc``) and are read
zero-copy by worker processes attaching the same /dev/shm file; smaller
objects are held in-band as Python values.  When arena occupancy crosses
``object_spilling_threshold`` (or allocation fails), the least-recently-
used sealed objects spill to ``object_spilling_dir`` and restore on
demand (plasma's spill/restore semantics).

Semantics carried over: objects are sealed-once immutable; ``get`` blocks
with timeout; storing a ``RayTaskError`` poisons the object — every get
raises it (task failure propagation).  Put listeners drive the dependency
manager (task args become ready) without polling.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Iterable, Sequence

from ..common.ids import ObjectID
from .serialization import RayError, RayTaskError, deserialize
from ..common import clock as _clk


class GetTimeoutError(RayError, TimeoutError):
    """ray.get timed out (reference: ``ray.exceptions.GetTimeoutError``)."""


class ObjectLostError(RayError):
    """Object was freed/lost and cannot be reconstructed (reference:
    ``ray.exceptions.ObjectLostError``)."""


class ObjectStoreFullError(RayError, MemoryError):
    """Arena and spill both exhausted (reference:
    ``ray.exceptions.ObjectStoreFullError``)."""


class RemoteObjectUnavailable(KeyError):
    """A read hit a metadata-only RemoteEntry: the bytes live on another
    node's plane and were not pulled first.  Read paths are expected to
    go through the pull manager; this surfacing means a caller skipped
    it."""


@dataclass
class ShmEntry:
    """Sealed serialized payload resident in the shared arena.

    ``pins`` counts descriptors currently handed out to workers (plasma's
    in-use semantics): a pinned entry is never spilled or freed, so the
    worker's zero-copy read cannot race a reallocation of its block."""
    offset: int
    size: int
    pins: int = 0


@dataclass
class SpillEntry:
    """Payload spilled to disk; restored to the arena on access."""
    path: str
    size: int


@dataclass
class RemoteEntry:
    """Metadata-only seal: the payload lives in ANOTHER node's store
    (rows per the object directory).  The head records these for objects
    sealed on agent machines so dependency tracking (`contains`,
    `on_ready`) works without the bytes ever transiting the head —
    materialization goes through the pull manager, which replaces this
    entry with real bytes via ``begin_ingest``/``commit`` (reference:
    the local plasma store simply lacks the object and the PullManager
    fetches it; here absence-with-metadata is an explicit entry because
    one store doubles as the owner's metadata table)."""
    size: int

# plasma_info() kinds that are directory-tracked and transferable
PLASMA_KINDS = ("shm", "spill", "remote")


def _NO_RELEASE() -> None:
    """Release hook for buffers that hold no pin (spill reads)."""


class MemoryStore:
    def __init__(self, arena=None, spill_dir: str | None = None,
                 direct_call_threshold: int | None = None,
                 spill_threshold: float | None = None):
        from ..common.config import get_config
        cfg = get_config()
        self._cv = threading.Condition()
        # LRU order: least-recently-touched first (spill victims)
        self._objects: "OrderedDict[ObjectID, object]" = OrderedDict()
        self._listeners: dict[ObjectID, list[Callable[[ObjectID], None]]] = {}
        self.arena = arena
        self._spill_dir = spill_dir
        self._threshold = (direct_call_threshold
                          if direct_call_threshold is not None
                          else cfg.max_direct_call_object_size)
        self._spill_frac = (spill_threshold if spill_threshold is not None
                            else cfg.object_spilling_threshold)
        self.spilled_bytes = 0
        self.restored_bytes = 0
        # deleted-while-pinned shm entries, keyed by (oid, offset) so a
        # re-seal + re-delete of the same object id cannot overwrite an
        # older zombie; the block is freed only when the last outstanding
        # descriptor is unpinned
        self._zombies: dict[tuple[ObjectID, int], ShmEntry] = {}

    # -- write --------------------------------------------------------------
    def put(self, object_id: ObjectID, value) -> None:
        """Seal an in-band Python value (first write wins; real bytes
        upgrade a metadata-only RemoteEntry)."""
        with self._cv:
            existing = self._objects.get(object_id)
            if existing is not None and \
                    not isinstance(existing, RemoteEntry):
                return
            self._objects[object_id] = value
            listeners = self._listeners.pop(object_id, ())
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    def put_serialized(self, object_id: ObjectID, data) -> None:
        """Seal a serialized payload, routing by size: large payloads go
        to the shared arena (zero-copy reads), small ones are held in-band
        as the deserialized value."""
        data = memoryview(data)
        if self.arena is None or data.nbytes <= self._threshold:
            self.put(object_id, deserialize(data))
            return
        with self._cv:
            existing = self._objects.get(object_id)
            if existing is not None and \
                    not isinstance(existing, RemoteEntry):
                return
            try:
                entry = self._shm_put_locked(data)
            except ObjectStoreFullError:
                # arena exhausted even after spilling (e.g. one payload
                # larger than the arena, or everything pinned): never
                # strand waiters — spill the payload straight to disk, or
                # hold it in-band when there is no spill dir (the restore
                # path's bytes fallback, in reverse)
                entry = self._spill_direct_locked(object_id, data)
                if entry is None:
                    entry = deserialize(data)
            self._objects[object_id] = entry
            listeners = self._listeners.pop(object_id, ())
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    def _shm_put_locked(self, data) -> ShmEntry:
        """Allocate+copy into the arena, spilling LRU victims as needed.
        Caller holds the lock."""
        from ..native import ArenaFullError
        if data.nbytes >= self.arena.capacity():
            # can NEVER fit: fail fast instead of evicting the whole
            # arena first (an over-capacity object would otherwise turn
            # every restore attempt into a full spill storm)
            raise ObjectStoreFullError(
                f"payload of {data.nbytes} bytes exceeds arena capacity "
                f"{self.arena.capacity()}")
        self._maybe_spill_locked(data.nbytes)
        while True:
            try:
                off = self.arena.alloc(data.nbytes)
                break
            except ArenaFullError:
                if not self._spill_one_locked():
                    raise ObjectStoreFullError(
                        f"object store full: cannot place {data.nbytes} "
                        f"bytes (capacity {self.arena.capacity()})")
        self.arena.write(off, data)
        return ShmEntry(off, data.nbytes)

    def _maybe_spill_locked(self, incoming: int) -> None:
        if self.arena is None:
            return
        budget = int(self.arena.capacity() * self._spill_frac)
        while self.arena.bytes_in_use() + incoming > budget:
            if not self._spill_one_locked():
                break

    def _spill_one_locked(self) -> bool:
        """Spill the least-recently-used UNPINNED shm object to disk.
        Pinned entries are skipped: a worker may hold their (offset, size)
        descriptor and read the block at any moment."""
        victim = None
        for oid, entry in self._objects.items():      # LRU first
            if isinstance(entry, ShmEntry) and entry.pins == 0:
                victim = (oid, entry)
                break
        if victim is None or self._spill_dir is None:
            return False
        oid, entry = victim
        path = self._write_spill_file(oid, self.arena.view(entry.offset,
                                                           entry.size))
        self.arena.free(entry.offset)
        self._objects[oid] = SpillEntry(path, entry.size)
        self.spilled_bytes += entry.size
        return True

    def _write_spill_file(self, object_id: ObjectID, data) -> str:
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir, object_id.hex())
        with open(path, "wb") as f:
            f.write(data)
        return path

    def _spill_direct_locked(self, object_id: ObjectID,
                             data) -> SpillEntry | None:
        """Payload that cannot enter the arena goes straight to disk
        (sealed as a SpillEntry); None when no spill dir is configured."""
        if self._spill_dir is None:
            return None
        path = self._write_spill_file(object_id, data)
        self.spilled_bytes += data.nbytes
        return SpillEntry(path, data.nbytes)

    def _restore_locked(self, object_id: ObjectID,
                        entry: SpillEntry) -> ShmEntry | bytes:
        """Bring a spilled payload back; prefer the arena (zero-copy for
        readers), fall back to raw bytes if it cannot fit."""
        with open(entry.path, "rb") as f:
            data = f.read()
        self.restored_bytes += len(data)
        try:
            shm = self._shm_put_locked(memoryview(data))
        except ObjectStoreFullError:
            return data
        os.unlink(entry.path)
        self._objects[object_id] = shm
        return shm

    def put_remote(self, object_id: ObjectID, size: int) -> None:
        """Seal a remote-resident object's METADATA (first write wins):
        the bytes live on another node's plane; local readers go through
        the pull manager, which ingests real bytes over this entry."""
        with self._cv:
            if object_id in self._objects:
                return
            self._objects[object_id] = RemoteEntry(size)
            listeners = self._listeners.pop(object_id, ())
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    # -- wire-level transfer (object plane) ----------------------------------
    def read_range(self, object_id: ObjectID, offset: int,
                   length: int) -> bytes | None:
        """One chunk of a sealed payload for an arena-to-arena transfer;
        None when the object has no local bytes (absent/remote/in-band).
        Shm reads copy under a transient pin so a concurrent spill/free
        cannot reallocate the block mid-read; spill reads go straight to
        the file without restoring.  A spill file vanishing mid-read is
        re-checked against the entry — a concurrent RESTORE unlinks the
        file while moving the bytes into the arena (the object is still
        live; only a true delete returns None)."""
        for _ in range(4):
            with self._cv:
                entry = self._objects.get(object_id)
                if isinstance(entry, ShmEntry):
                    # a peer asking past the end (stale size metadata,
                    # malformed op_read) must not produce a negative-
                    # length arena view on the RPC handler thread
                    if offset < 0 or offset >= entry.size:
                        return b""
                    entry.pins += 1
                    pin = (object_id, entry.offset)
                    view = self.arena.view(entry.offset + offset,
                                           min(length,
                                               entry.size - offset))
                elif isinstance(entry, SpillEntry):
                    if offset < 0:
                        return b""      # seek would raise
                    path = entry.path
                    view = None
                else:
                    return None
            if view is not None:
                try:
                    return bytes(view)
                finally:
                    self.unpin([pin])
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    return f.read(length)
            except OSError:
                continue        # restore/delete raced: re-check entry
        return None

    def read_range_view(self, object_id: ObjectID, offset: int,
                        length: int):
        """Zero-copy variant of ``read_range`` for the raw data channel:
        ``(buffer, release)`` where the buffer is an arena memoryview
        pinned until ``release()`` runs (the RPC server calls it once
        the bytes are on the socket), or plain spill-file bytes with a
        no-op release.  ``(None, None)`` when the object has no local
        bytes."""
        for _ in range(4):
            with self._cv:
                entry = self._objects.get(object_id)
                if isinstance(entry, ShmEntry):
                    if offset < 0 or offset >= entry.size:
                        return b"", _NO_RELEASE
                    entry.pins += 1
                    pin = (object_id, entry.offset)
                    view = self.arena.view(entry.offset + offset,
                                           min(length,
                                               entry.size - offset))
                    return view, lambda: self.unpin([pin])
                if isinstance(entry, SpillEntry):
                    if offset < 0:
                        return b"", _NO_RELEASE
                    path = entry.path
                else:
                    return None, None
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    return f.read(length), _NO_RELEASE
            except OSError:
                continue        # restore/delete raced: re-check entry
        return None, None

    def begin_ingest(self, object_id: ObjectID, size: int):
        """Start receiving a remote object's bytes: returns an
        ``IngestHandle`` (write chunks, then commit — which seals over
        any RemoteEntry), or None when local bytes already exist.
        Prefers an arena block (spilling LRU victims for room); falls
        back to writing a spill file when the arena cannot take it."""
        with self._cv:
            entry = self._objects.get(object_id)
            if entry is not None and not isinstance(entry, RemoteEntry):
                return None
            if self.arena is not None and size > self._threshold:
                try:
                    shm = self._alloc_ingest_locked(size)
                    return _IngestHandle(self, object_id, shm=shm)
                except ObjectStoreFullError:
                    pass
        if self._spill_dir is None:
            # tiny object or no spill dir: buffer in memory
            return _IngestHandle(self, object_id, buf=bytearray(size))
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir,
                            object_id.hex() + ".ingest")
        return _IngestHandle(self, object_id, path=path, size=size)

    def _alloc_ingest_locked(self, size: int) -> ShmEntry:
        """Arena block for an in-flight ingest (caller holds the lock).
        Pinned from birth so the spill scan never victimizes a block
        that is still being written."""
        shm = self._shm_put_locked_alloc(size)
        shm.pins = 1
        return shm

    def _shm_put_locked_alloc(self, size: int) -> ShmEntry:
        """Allocate (no copy) with the same eviction discipline as
        ``_shm_put_locked``."""
        from ..native import ArenaFullError
        if size >= self.arena.capacity():
            raise ObjectStoreFullError(
                f"payload of {size} bytes exceeds arena capacity "
                f"{self.arena.capacity()}")
        self._maybe_spill_locked(size)
        while True:
            try:
                off = self.arena.alloc(size)
                return ShmEntry(off, size)
            except ArenaFullError:
                if not self._spill_one_locked():
                    raise ObjectStoreFullError(
                        f"object store full: cannot place {size} bytes "
                        f"(capacity {self.arena.capacity()})") from None

    def _commit_ingest(self, object_id: ObjectID, entry) -> None:
        """Seal ingested bytes over an absent or RemoteEntry slot."""
        with self._cv:
            existing = self._objects.get(object_id)
            if existing is not None and \
                    not isinstance(existing, RemoteEntry):
                # lost the race to another ingest/seal: discard ours
                self._release_entry(entry)
                return
            if isinstance(entry, ShmEntry):
                entry.pins = 0      # birth pin released at seal
            self._objects[object_id] = entry
            listeners = self._listeners.pop(object_id, ())
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    def drop_remote_entry(self, object_id: ObjectID) -> None:
        """Remove a metadata-only RemoteEntry (its backing copies are
        gone — node death).  Real local entries are left alone; waiters
        re-park on absence and wake at the re-seal or poison."""
        with self._cv:
            if isinstance(self._objects.get(object_id), RemoteEntry):
                del self._objects[object_id]

    def delete(self, object_ids: Iterable[ObjectID]) -> None:
        with self._cv:
            for oid in object_ids:
                entry = self._objects.pop(oid, None)
                if isinstance(entry, ShmEntry) and entry.pins > 0:
                    # a worker still holds a descriptor: defer the free
                    # until the last unpin (plasma: delete waits for the
                    # in-use count to drop)
                    self._zombies[(oid, entry.offset)] = entry
                    continue
                self._release_entry(entry)

    def inline_bytes(self, oid, desc) -> bytes:
        """Copy a PINNED shm descriptor's payload and release the pin —
        the in-band form shipped to workers that share no arena (remote
        node agents, ``raylet.inline_objects``)."""
        try:
            return bytes(self.arena.view(desc[1], desc[2]))
        finally:
            self.unpin([(oid, desc[1])])

    def unpin(self, pins: Iterable) -> None:
        """Release descriptor pins taken by ``descriptor_of`` /
        ``get_descriptors_blocking`` (one unpin per shm descriptor handed
        out).  Each pin is an ObjectID or an ``(ObjectID, offset)`` pair;
        the offset disambiguates a deleted-while-pinned block from a
        later re-seal of the same object id (the offset is unique while
        the block stays allocated).  Frees deleted-while-pinned blocks at
        pin count zero."""
        with self._cv:
            for p in pins:
                oid, off = p if isinstance(p, tuple) else (p, None)
                entry = self._objects.get(oid)
                if isinstance(entry, ShmEntry) and \
                        (off is None or entry.offset == off):
                    if entry.pins > 0:
                        entry.pins -= 1
                    continue
                if off is not None:
                    zkey = (oid, off)
                else:       # id-only unpin: any zombie of this object
                    zkey = next((k for k in self._zombies if k[0] == oid),
                                None)
                z = self._zombies.get(zkey) if zkey is not None else None
                if z is not None:
                    z.pins -= 1
                    if z.pins <= 0:
                        del self._zombies[zkey]
                        self._release_entry(z)
            self._cv.notify_all()

    def _release_entry(self, entry) -> None:
        if isinstance(entry, ShmEntry) and self.arena is not None:
            self.arena.free(entry.offset)
        elif isinstance(entry, SpillEntry):
            try:
                os.unlink(entry.path)
            except OSError:
                pass

    def routes_to_plasma(self, nbytes: int) -> bool:
        """Will a payload of this size seal into the arena (directory-
        tracked)?  Callers use this to pre-register locations BEFORE the
        seal: sealing wakes dependent-task placement, so the directory
        must already know where the bytes live or the locality probe
        races an empty entry."""
        return self.arena is not None and nbytes > self._threshold

    def plasma_info(self, object_id: ObjectID) -> tuple[str | None, int]:
        """(kind, size): kind is "shm" | "spill" (plasma-routed, has
        directory locations), "inband" (ships with specs), or None
        (absent)."""
        with self._cv:
            e = self._objects.get(object_id)
            if isinstance(e, ShmEntry):
                return "shm", e.size
            if isinstance(e, SpillEntry):
                return "spill", e.size
            if isinstance(e, RemoteEntry):
                return "remote", e.size
            return (None, 0) if e is None else ("inband", 0)

    def poison(self, object_id: ObjectID, error) -> None:
        """Replace a LOST object's entry with an in-band error value so
        every current and future reader surfaces the loss instead of
        hanging (reference: lost plasma objects raise ObjectLostError).
        The only sanctioned break of seal-once immutability; pinned
        blocks park as zombies until their descriptors release."""
        with self._cv:
            entry = self._objects.get(object_id)
            if isinstance(entry, ShmEntry) and entry.pins > 0:
                self._zombies[(object_id, entry.offset)] = entry
            else:
                self._release_entry(entry)
            self._objects[object_id] = error
            listeners = self._listeners.pop(object_id, ())
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    # -- materialization ----------------------------------------------------
    def _value_locked(self, object_id: ObjectID):
        """Deserialize/restore an entry into a Python value; touches LRU."""
        entry = self._objects[object_id]
        self._objects.move_to_end(object_id)
        if isinstance(entry, RemoteEntry):
            raise RemoteObjectUnavailable(
                f"object {object_id.hex()[:12]} is resident on a remote "
                "plane; pull it first")
        if isinstance(entry, SpillEntry):
            entry = self._restore_locked(object_id, entry)
            if isinstance(entry, bytes):
                return deserialize(entry)
        if isinstance(entry, ShmEntry):
            return deserialize(self.arena.view(entry.offset, entry.size))
        return entry

    def _descriptor_locked(self, object_id: ObjectID):
        """Wire form for worker replies: ("v", value) in-band, or
        ("s", offset, size) for zero-copy shm reads.  Spilled objects are
        restored first; if the arena can't take them, bytes go in-band.
        Shm descriptors PIN the entry — the caller owes one
        ``unpin([object_id])`` once the worker is done with the block."""
        entry = self._objects[object_id]
        self._objects.move_to_end(object_id)
        if isinstance(entry, RemoteEntry):
            raise RemoteObjectUnavailable(
                f"object {object_id.hex()[:12]} is resident on a remote "
                "plane; pull it first")
        if isinstance(entry, SpillEntry):
            entry = self._restore_locked(object_id, entry)
            if isinstance(entry, bytes):
                return ("b", entry)
        if isinstance(entry, ShmEntry):
            entry.pins += 1
            return ("s", entry.offset, entry.size)
        return ("v", entry)

    # -- read ---------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._cv:
            return object_id in self._objects

    def _await_locked(self, object_ids: Sequence[ObjectID],
                      deadline: float | None) -> bool:
        """Wait (caller holds lock) until all ids exist. False on timeout."""
        while True:
            missing = [o for o in object_ids if o not in self._objects]
            if not missing:
                return True
            if deadline is not None:
                remaining = deadline - _clk.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            else:
                self._cv.wait()

    def get(self, object_ids: Sequence[ObjectID],
            timeout: float | None = None) -> list:
        """Blocking fetch of all ids (in order). Raises stored errors."""
        deadline = None if timeout is None else _clk.monotonic() + timeout
        with self._cv:
            if not self._await_locked(object_ids, deadline):
                missing = sum(o not in self._objects for o in object_ids)
                raise GetTimeoutError(
                    f"get timed out; {missing} of {len(object_ids)} "
                    "objects not ready")
            values = [self._value_locked(o) for o in object_ids]
        for v in values:
            if isinstance(v, RayTaskError):
                raise v.cause if v.cause is not None else v
        return values

    def wait(self, object_ids: Sequence[ObjectID], num_returns: int,
             timeout: float | None = None
             ) -> tuple[list[ObjectID], list[ObjectID]]:
        """ray.wait semantics: (ready, not_ready), order-preserving."""
        deadline = None if timeout is None else _clk.monotonic() + timeout
        with self._cv:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    break
                if deadline is not None:
                    remaining = deadline - _clk.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            ready_set = set(ready[:num_returns]) if len(ready) > num_returns \
                else set(ready)
            ready_list = [o for o in object_ids if o in ready_set]
            not_ready = [o for o in object_ids if o not in ready_set]
            return ready_list, not_ready

    def get_raw_presence(self, object_ids: Sequence[ObjectID],
                         timeout: float | None = None) -> bool:
        """Block until every id EXISTS (any entry kind, including
        metadata-only RemoteEntry); no materialization.  False on
        timeout."""
        deadline = None if timeout is None else _clk.monotonic() + timeout
        with self._cv:
            return self._await_locked(object_ids, deadline)

    def get_raw_blocking(self, object_ids: Sequence[ObjectID],
                         timeout: float | None = None) -> list | None:
        """Blocking fetch WITHOUT error unwrap — stored RayTaskError values
        are returned as values (the worker-side get re-raises them).
        Returns None on timeout."""
        deadline = None if timeout is None else _clk.monotonic() + timeout
        with self._cv:
            if not self._await_locked(object_ids, deadline):
                return None
            return [self._value_locked(o) for o in object_ids]

    def get_descriptors_blocking(self, object_ids: Sequence[ObjectID],
                                 timeout: float | None = None
                                 ) -> list | None:
        """Blocking fetch of wire descriptors for a worker reply: shm
        objects ship as (offset, size) for zero-copy reads, small ones as
        in-band values.  Returns None on timeout."""
        deadline = None if timeout is None else _clk.monotonic() + timeout
        with self._cv:
            if not self._await_locked(object_ids, deadline):
                return None
            return [self._descriptor_locked(o) for o in object_ids]

    def peek(self, object_id: ObjectID):
        """Non-blocking read (materializes); KeyError if absent."""
        with self._cv:
            return self._value_locked(object_id)

    def descriptor_of(self, object_id: ObjectID):
        """Non-blocking wire descriptor; KeyError if absent."""
        with self._cv:
            return self._descriptor_locked(object_id)

    def error_of(self, object_id: ObjectID):
        """Non-blocking: the stored ``RayTaskError`` if this object's
        entry is an in-band error value, else None.  Error results are
        always stored in-band (never shm/spill), so this never
        materializes a data payload — completion observers use it to
        classify a sealed result without paying a deserialize."""
        with self._cv:
            e = self._objects.get(object_id)
        return e if isinstance(e, RayTaskError) else None

    # -- listeners (dependency manager hook) --------------------------------
    def on_ready(self, object_id: ObjectID,
                 callback: Callable[[ObjectID], None]) -> None:
        """Invoke ``callback(oid)`` once the object exists (immediately if
        it already does). Callback runs without the store lock held."""
        with self._cv:
            if object_id not in self._objects:
                self._listeners.setdefault(object_id, []).append(callback)
                return
        callback(object_id)

    def cancel_on_ready(self, object_id: ObjectID, callback) -> None:
        """Deregister a pending ``on_ready`` listener (no-op if it already
        fired or was never registered) — abandoning waiters must not leak
        closures."""
        with self._cv:
            lst = self._listeners.get(object_id)
            if lst is not None:
                try:
                    lst.remove(callback)
                except ValueError:
                    return
                if not lst:
                    del self._listeners[object_id]

    # -- introspection ------------------------------------------------------
    def size(self) -> int:
        with self._cv:
            return len(self._objects)

    def list_objects(self) -> list[tuple]:
        """(object_id, size_bytes, kind) rows for the state API; size
        is known for shm/spilled entries, -1 for in-band values."""
        with self._cv:
            out = []
            for oid, entry in self._objects.items():
                if isinstance(entry, ShmEntry):
                    out.append((oid, entry.size, "shm"))
                elif isinstance(entry, SpillEntry):
                    out.append((oid, entry.size, "spilled"))
                elif isinstance(entry, RemoteEntry):
                    out.append((oid, entry.size, "remote"))
                else:
                    out.append((oid, -1, "in_band"))
            return out

    def stats(self) -> dict:
        with self._cv:
            shm = sum(isinstance(e, ShmEntry)
                      for e in self._objects.values())
            spilled = sum(isinstance(e, SpillEntry)
                          for e in self._objects.values())
            remote = sum(isinstance(e, RemoteEntry)
                         for e in self._objects.values())
            return {
                "num_objects": len(self._objects),
                "num_shm": shm,
                "num_spilled": spilled,
                "num_remote": remote,
                "num_pinned": sum(
                    isinstance(e, ShmEntry) and e.pins > 0
                    for e in self._objects.values()),
                "arena_bytes_in_use": (self.arena.bytes_in_use()
                                       if self.arena else 0),
                "arena_capacity": (self.arena.capacity()
                                   if self.arena else 0),
                "spilled_bytes": self.spilled_bytes,
                "restored_bytes": self.restored_bytes,
            }


class _IngestHandle:
    """Destination side of one arena-to-arena transfer: chunks land
    directly in their final home (arena block, spill file, or an
    in-memory buffer for sub-threshold payloads) — no whole-object
    staging copy.  ``commit`` seals; ``abort`` releases."""

    def __init__(self, store: MemoryStore, object_id: ObjectID,
                 shm: ShmEntry | None = None, path: str | None = None,
                 size: int = 0, buf: bytearray | None = None):
        self._store = store
        self._oid = object_id
        self._shm = shm
        self._path = path
        self._size = size if shm is None else shm.size
        self._buf = buf
        self._file = open(path, "wb") if path is not None else None
        self._done = False

    def prefault(self) -> None:
        """Touch one byte per page of an arena ingest block so the
        first-touch faults (tmpfs page allocation + zeroing — the bulk
        of a cold landing write's cost) are paid here, overlapped with
        the network transfer, instead of serializing into the chunk
        landings.  Native + GIL-free (``Arena.touch``) so the walk runs
        on a spare core instead of convoying the reader thread; reads
        only, safe concurrent with ``write``."""
        if self._shm is None:
            return
        try:
            self._store.arena.touch(self._shm.offset, self._size)
        except (ValueError, AttributeError):
            pass        # arena closed mid-walk: best effort

    def view(self, offset: int, length: int):
        """Writable view of ``[offset, offset+length)`` in the landing
        block, for receiving wire bytes straight into their final home
        (shm ingest only; None otherwise — callers fall back to the
        buffered receive + ``write`` path)."""
        if self._shm is None or offset + length > self._size:
            return None
        return self._store.arena.view(self._shm.offset + offset, length)

    def write(self, offset: int, data: bytes) -> None:
        if self._shm is not None:
            self._store.arena.write(self._shm.offset + offset,
                                    memoryview(data))
        elif self._buf is not None:
            self._buf[offset:offset + len(data)] = data
        else:
            self._file.seek(offset)
            self._file.write(data)

    def commit(self) -> None:
        if self._done:
            return
        self._done = True
        if self._shm is not None:
            self._store._commit_ingest(self._oid, self._shm)
        elif self._buf is not None:
            # sub-threshold payload: seal as the in-band value, like
            # put_serialized's small route
            self._store._commit_ingest(self._oid,
                                       deserialize(bytes(self._buf)))
        else:
            self._file.close()
            final = self._path[:-len(".ingest")]
            os.replace(self._path, final)
            self._store.spilled_bytes += self._size
            self._store._commit_ingest(self._oid,
                                       SpillEntry(final, self._size))

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        if self._shm is not None:
            with self._store._cv:
                self._store.arena.free(self._shm.offset)
        elif self._file is not None:
            self._file.close()
            try:
                os.unlink(self._path)
            except OSError:
                pass
