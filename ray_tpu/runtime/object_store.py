"""In-process object store (the core-worker memory store analogue).

Reference parity: every upstream worker embeds an in-process memory store
for small/in-band objects next to the plasma provider for large ones
(``src/ray/core_worker/store_provider/memory_store/`` — SURVEY.md §1 layer
7; mount empty).  This is the driver/worker-side store of the single-node
slice; the shared-memory arena store (plasma analogue) plugs in behind the
same interface for large objects.

Semantics carried over: objects are sealed-once immutable; ``get`` blocks
with timeout; storing a ``RayTaskError`` poisons the object — every get
raises it (task failure propagation).  Put listeners drive the dependency
manager (task args become ready) without polling.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Sequence

from ..common.ids import ObjectID
from .serialization import RayError, RayTaskError


class GetTimeoutError(RayError, TimeoutError):
    """ray.get timed out (reference: ``ray.exceptions.GetTimeoutError``)."""


class ObjectLostError(RayError):
    """Object was freed/lost and cannot be reconstructed (reference:
    ``ray.exceptions.ObjectLostError``)."""


class MemoryStore:
    def __init__(self):
        self._cv = threading.Condition()
        self._objects: dict[ObjectID, object] = {}
        self._listeners: dict[ObjectID, list[Callable[[ObjectID], None]]] = {}

    # -- write --------------------------------------------------------------
    def put(self, object_id: ObjectID, value) -> None:
        with self._cv:
            if object_id in self._objects:
                return                      # sealed-once: first write wins
            self._objects[object_id] = value
            listeners = self._listeners.pop(object_id, ())
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    def delete(self, object_ids: Iterable[ObjectID]) -> None:
        with self._cv:
            for oid in object_ids:
                self._objects.pop(oid, None)

    # -- read ---------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._cv:
            return object_id in self._objects

    def get(self, object_ids: Sequence[ObjectID],
            timeout: float | None = None) -> list:
        """Blocking fetch of all ids (in order). Raises stored errors."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                missing = [o for o in object_ids if o not in self._objects]
                if not missing:
                    break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise GetTimeoutError(
                            f"get timed out; {len(missing)} of "
                            f"{len(object_ids)} objects not ready")
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            values = [self._objects[o] for o in object_ids]
        for v in values:
            if isinstance(v, RayTaskError):
                raise v.cause if v.cause is not None else v
        return values

    def wait(self, object_ids: Sequence[ObjectID], num_returns: int,
             timeout: float | None = None
             ) -> tuple[list[ObjectID], list[ObjectID]]:
        """ray.wait semantics: (ready, not_ready), order-preserving."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            ready_set = set(ready[:num_returns]) if len(ready) > num_returns \
                else set(ready)
            ready_list = [o for o in object_ids if o in ready_set]
            not_ready = [o for o in object_ids if o not in ready_set]
            return ready_list, not_ready

    def get_raw_blocking(self, object_ids: Sequence[ObjectID],
                         timeout: float | None = None) -> list | None:
        """Blocking fetch WITHOUT error unwrap — stored RayTaskError values
        are returned as values (the worker-side get re-raises them).
        Returns None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while any(o not in self._objects for o in object_ids):
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            return [self._objects[o] for o in object_ids]

    def peek(self, object_id: ObjectID):
        """Non-blocking raw read (no error unwrap); KeyError if absent."""
        with self._cv:
            return self._objects[object_id]

    # -- listeners (dependency manager hook) --------------------------------
    def on_ready(self, object_id: ObjectID,
                 callback: Callable[[ObjectID], None]) -> None:
        """Invoke ``callback(oid)`` once the object exists (immediately if
        it already does). Callback runs without the store lock held."""
        with self._cv:
            if object_id not in self._objects:
                self._listeners.setdefault(object_id, []).append(callback)
                return
        callback(object_id)

    def size(self) -> int:
        with self._cv:
            return len(self._objects)
