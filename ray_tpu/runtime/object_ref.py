"""ObjectRef — the future type returned by task submission and put.

Reference parity: ``ray.ObjectRef`` wraps the 28-byte ObjectID plus owner
metadata, and its construction/destruction drive the owner's
``ReferenceCounter`` (``python/ray/includes/object_ref.pxi`` — SURVEY.md
§1 layers 7/9; mount empty).  Resolution goes through ``ray_tpu.get``.

The counter hook is process-global and installed only in the owner
(driver) process — worker processes deserialize ObjectRefs freely with no
counting (their borrows are covered by the retained TaskSpec's strong
references on the driver side).  Each instance latches the counter it
registered with so an uninstall (cluster teardown) never produces an
unbalanced decref.
"""

from __future__ import annotations

import contextlib
import threading

from ..common.ids import ObjectID

_counter = None     # the owner-process ReferenceCounter, or None
_suppress = threading.local()   # per-thread: refs built uncounted
_collect = threading.local()    # per-thread: refs pickled inside a payload


def install_counter(counter) -> None:
    """Make new ObjectRefs in this process count against ``counter``."""
    global _counter
    _counter = counter


def install_counter_if_absent(counter) -> bool:
    """Install only when no counter is active.  A ClientRuntime created
    INSIDE a process that already counts (the head, a worker) must not
    steal that process's refs — they keep their original holder and the
    embedded client rides that lifetime."""
    global _counter
    if _counter is not None:
        return False
    _counter = counter
    return True


def uninstall_counter(counter) -> None:
    global _counter
    if _counter is counter:
        _counter = None


@contextlib.contextmanager
def counter_suppressed():
    """ObjectRefs built on THIS thread inside the block are uncounted.

    The head daemon deserializes client-submitted specs/actor args under
    this: the client's own refs are outside the owner counter, so a
    counted server-side twin would eventually decref to zero (lineage
    eviction, actor death) and reclaim objects the client still holds —
    client-held objects take the worker-frame conservative-leak
    ownership instead."""
    prev = getattr(_suppress, "on", False)
    _suppress.on = True
    try:
        yield
    finally:
        _suppress.on = prev


_gen_transfer = threading.local()


@contextlib.contextmanager
def transfer_generators():
    """Collect ObjectRefGenerators pickled on THIS thread inside the
    block WITHOUT marking them transferred; the caller marks them
    (``mark_transferred``) only after the serialized bytes actually
    ship to a consumer.  Pickles outside any such block keep the
    legacy immediate one-shot side effect (see ``__reduce__``)."""
    prev = getattr(_gen_transfer, "gens", None)
    _gen_transfer.gens = []
    try:
        yield _gen_transfer.gens
    finally:
        _gen_transfer.gens = prev


def mark_transferred(gens) -> None:
    """The serialized frame containing these generators was handed to
    its consumer: consumption ownership has moved."""
    for g in gens:
        g._transferred = True


@contextlib.contextmanager
def ref_collector():
    """Record every ObjectRef pickled on THIS thread inside the block.

    Serializing a result/put payload under this yields the ids of the
    refs nested in it; the head registers them as CONTAINED in the
    enclosing object, which keeps them alive until it is reclaimed —
    closing the window where the producer's refs die before the
    consumer deserializes (upstream: ownership info travels with the
    serialized ref)."""
    prev = getattr(_collect, "refs", None)
    _collect.refs = []
    try:
        yield _collect.refs
    finally:
        _collect.refs = prev


class ObjectRefGenerator:
    """The consumer's side of a streaming-generator task
    (``num_returns="streaming"``): iterating yields ObjectRefs for the
    generator's items as they seal, with consumption acks driving the
    producer's backpressure window (reference: ``ObjectRefGenerator``,
    core worker streaming-generator protocol — SURVEY.md §1 layer 7;
    mount empty).

    The runtime must expose ``stream_wait(task_id, index, timeout)`` ->
    (sealed, done, error) and ``stream_ack(task_id, consumed)`` — the
    driver implements them on the TaskManager; the head proxies them
    for clients."""

    def __init__(self, task_id, runtime=None):
        self._task_id = task_id
        self._rt = runtime
        self._i = 0
        self._closed = False
        self._transferred = False
        # deserialized copies carry no runtime: they are the TRANSFER
        # target of a one-shot stream (see close()/__reduce__)
        self._from_wire = runtime is None

    def _runtime(self):
        if self._rt is None:    # deserialized: rebind to this process
            from .. import api
            self._rt = api._get_runtime()
        return self._rt

    @staticmethod
    def _unpack(reply):
        """(sealed, done, error, known) — older 3-field runtimes imply
        known=True."""
        if len(reply) == 4:
            return reply
        sealed, done, error = reply
        return sealed, done, error, True

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        rt = self._runtime()
        sealed, done, error, known = self._unpack(
            rt.stream_wait(self._task_id, self._i, 2.0))
        if self._i >= sealed and not done:
            # no progress in the grace window: re-ack our position (a
            # retried producer restarts with an empty ack table and
            # only this unblocks its backpressure), then wait for real
            rt.stream_ack(self._task_id, self._i)
            sealed, done, error, known = self._unpack(
                rt.stream_wait(self._task_id, self._i, None))
        if not known and self._from_wire and self._i == 0:
            # a deserialized copy against a REAPED stream: the one-shot
            # stream was consumed elsewhere (e.g. this consumer task
            # retried after already draining it) — fail loudly rather
            # than yielding a silently empty stream
            self._closed = True
            raise RuntimeError(
                "stream already consumed: ObjectRefGenerators are "
                "one-shot, and this copy arrived after the stream was "
                "drained and reaped (generator args are incompatible "
                "with task retries)")
        if self._i >= sealed:
            self.close()
            if error is not None:
                raise error.cause if getattr(error, "cause", None) \
                    else error
            raise StopIteration
        self._i += 1
        from ..common.ids import ObjectID
        ref = ObjectRef(ObjectID.for_task_return(self._task_id,
                                                 self._i))
        rt.stream_ack(self._task_id, self._i)
        return ref

    def close(self) -> None:
        """Finish with the stream: cancels a still-running producer and
        reclaims sealed-but-unconsumed items.  Called automatically at
        exhaustion and at garbage collection.  A generator that was
        SERIALIZED (shipped into a task) transferred its consumption
        ownership — the local copy's close/GC must not cancel the
        stream out from under the new consumer."""
        if self._closed:
            return
        self._closed = True
        if self._transferred:
            return
        try:
            self._runtime().stream_close(self._task_id, self._i)
        except Exception:   # noqa: BLE001 — teardown/GC: best-effort
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:   # noqa: BLE001
            pass

    @property
    def task_id(self):
        return self._task_id

    def __reduce__(self):
        gens = getattr(_gen_transfer, "gens", None)
        if gens is not None:
            # inside a transfer_generators() block (task/actor-call
            # serialization): the sender marks us transferred only
            # AFTER the bytes actually ship — a submit that fails after
            # arg serialization keeps the local copy's close/cancel
            gens.append(self)
        else:
            # stray pickle (deepcopy, logging, debug dumps): one-shot
            # semantics apply immediately — the copy consumes, and this
            # instance's close/cancel is permanently disabled.  If you
            # hit this from a non-transfer pickle, don't pickle
            # generators outside task submission.
            self._transferred = True
        return (ObjectRefGenerator, (self._task_id, None))


def serialize_collecting(value) -> tuple[bytes, list[bytes]]:
    """Serialize ``value`` and return (payload, binary ids of every
    ObjectRef pickled inside it) — the shared form of the
    seal-with-containment pattern used by puts and result payloads."""
    from .serialization import serialize
    with ref_collector() as got:
        data = serialize(value)
    return data, [o.binary() for o in got]


class ObjectRef:
    __slots__ = ("_id", "_ct")

    def __init__(self, object_id: ObjectID):
        self._id = object_id
        ct = None if getattr(_suppress, "on", False) else _counter
        self._ct = ct
        if ct is not None:
            ct.incref(object_id)

    def __del__(self):
        ct = self._ct
        if ct is not None:
            try:
                ct.decref(self._id)
            except Exception:
                pass        # interpreter teardown: counter may be gone

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def __reduce__(self):
        got = getattr(_collect, "refs", None)
        if got is not None:
            got.append(self._id)
        return (ObjectRef, (self._id,))

    def __await__(self):
        """``await ref`` inside an async actor resolves the object
        (reference: ObjectRefs are awaitable in async actors).  The
        blocking get runs on the loop's default executor so the event
        loop keeps serving other coroutines."""
        return self._resolve_async().__await__()

    async def _resolve_async(self):
        import asyncio
        loop = asyncio.get_running_loop()

        def blocking_get():
            from .. import api
            return api.get(self)
        return await loop.run_in_executor(None, blocking_get)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]}…)"
