"""ObjectRef — the future type returned by task submission and put.

Reference parity: ``ray.ObjectRef`` wraps the 28-byte ObjectID plus owner
metadata (``python/ray/includes/object_ref.pxi`` — SURVEY.md §1 layer 9;
mount empty).  Resolution goes through ``ray_tpu.get``.
"""

from __future__ import annotations

from ..common.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id",)

    def __init__(self, object_id: ObjectID):
        self._id = object_id

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def __reduce__(self):
        return (ObjectRef, (self._id,))

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]}…)"
