"""Dashboard: the head's HTTP observability surface.

Reference parity: the reference runs a dashboard web server on the head
(aiohttp app + per-node agents) exposing cluster state — nodes, actors,
tasks, objects, placement groups, jobs — alongside the metrics/state
APIs (``python/ray/dashboard/`` — SURVEY.md §1 layer 12, §2.2; mount
empty).  This rebuild's version is a dependency-free stdlib HTTP server
in the head/driver process:

- ``GET /``                      one-page HTML overview (auto-refresh)
- ``GET /api/summary``           cluster totals (resources, tasks, actors,
                                 store, nodes)
- ``GET /api/nodes|actors|tasks|objects|placement_groups``
                                 state-API rows as JSON
- ``GET /api/jobs``              submitted jobs (when a JobManager is
                                 attached, i.e. under the head daemon)
- ``GET /api/timeline``          Chrome-trace events
- ``GET /metrics``               Prometheus text (same renderer as the
                                 ``metrics_export_port`` endpoint)

Enabled by the ``dashboard_port``/``dashboard_host`` config knobs
(port 0 disables).  Everything is computed at request time from live
runtime objects — no collector thread.
"""

from __future__ import annotations

import html
import json
from collections import Counter

from .http_server import BackgroundHTTPServer


class Dashboard(BackgroundHTTPServer):
    def __init__(self, cluster, port: int = 0,
                 host: str = "127.0.0.1", job_manager=None):
        self._cluster = cluster
        self._jobs = job_manager
        super().__init__(host=host, port=port, name="dashboard")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def attach_jobs(self, job_manager) -> None:
        self._jobs = job_manager

    # -- routing -------------------------------------------------------------
    def route(self, request) -> None:
        path = request.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/":
            self.reply(request, self._render_index().encode(),
                       "text/html; charset=utf-8")
            return
        if path == "/metrics":
            from .metrics import render_metrics
            self.reply(request, render_metrics(self._cluster).encode(),
                       "text/plain; version=0.0.4")
            return
        if path.startswith("/api/"):
            payload = self._api(path[len("/api/"):])
            if payload is not None:
                self.reply(request, json.dumps(payload).encode(),
                           "application/json")
                return
        self.not_found(request)

    # -- data ----------------------------------------------------------------
    def _api(self, name: str):
        from ..util import state
        if name == "summary":
            return self._summary()
        if name == "nodes":
            return state.list_nodes()
        if name == "actors":
            return state.list_actors()
        if name == "tasks":
            return state.list_tasks()
        if name == "objects":
            return state.list_objects()
        if name == "placement_groups":
            return state.list_placement_groups()
        if name == "timeline":
            return self._cluster.events.timeline()
        if name == "stacks":
            # live all-thread stacks of every worker (py-spy analogue)
            got = self._cluster.dump_worker_stacks(timeout=5.0)
            return {f"{r}:{i}": text for (r, i), text in got.items()}
        if name == "jobs":
            return self._jobs.list() if self._jobs is not None else []
        if name == "leases":
            try:
                from ..leasing import aggregate_stats
                return aggregate_stats()
            except Exception:   # noqa: BLE001 — lease plane disabled
                return {}
        if name == "serve":
            out = {}
            try:
                from ..serve.router import request_plane_stats
                out["deployments"] = request_plane_stats()
            except Exception:   # noqa: BLE001 — serve absent/unused
                out["deployments"] = {}
            try:
                from ..serve.gossip import board
                out["gossip"] = board.stats()
            except Exception:   # noqa: BLE001
                pass
            loans = getattr(self._cluster, "loans", None)
            if loans is not None:
                out["loans"] = loans.stats()
            return out
        if name == "versions":
            try:
                from ..versioning import VersionRegistry
                return VersionRegistry().all()
            except Exception:   # noqa: BLE001 — versioning absent/unused
                return {}
        if name == "broadcasts":
            cluster = self._cluster
            out = {}
            broadcasts = getattr(cluster, "broadcasts", None)
            if broadcasts is not None:
                out.update(broadcasts.stats())
            plane = getattr(cluster, "plane", None)
            if plane is not None:
                out.update(plane.bcast.stats())
            return out
        if name == "health":
            from ..rpc import breaker, chaos
            cluster = self._cluster
            out = cluster.health.stats()
            out["suspect_rows"] = cluster.crm.suspect_rows()
            out["breakers"] = breaker.stats()
            out["chaos"] = chaos.status()
            plane = getattr(cluster, "plane", None)
            if plane is not None:
                out["blacklisted_sources"] = plane.blacklisted_sources()
            return out
        return None

    def _summary(self, nodes=None, actors=None, tasks=None) -> dict:
        """Rows may be passed in by a caller that already listed them
        (the index page) so one render never walks the state twice."""
        from .. import api
        from ..util import state
        nodes = state.list_nodes() if nodes is None else nodes
        actors = state.list_actors() if actors is None else actors
        tasks = state.list_tasks() if tasks is None else tasks
        task_counts = Counter(r["state"] for r in tasks)
        actor_counts = Counter(r["state"] for r in actors)
        return {
            "nodes": len(nodes),
            "cluster_resources": api.cluster_resources(),
            "available_resources": api.available_resources(),
            "tasks": {"total": len(tasks),
                      "by_state": dict(task_counts)},
            "actors": {"total": len(actors),
                       "by_state": dict(actor_counts)},
            "store": self._cluster.store.stats(),
            "jobs": (self._jobs.list() if self._jobs is not None else []),
        }

    # -- HTML ----------------------------------------------------------------
    def _render_index(self) -> str:
        from ..util import state
        nodes = state.list_nodes()
        actors = state.list_actors()
        tasks = state.list_tasks()
        pgs = state.list_placement_groups()
        s = self._summary(nodes=nodes, actors=actors, tasks=tasks)

        def table(rows: list[dict], columns: list[str]) -> str:
            head = "".join(f"<th>{html.escape(c)}</th>" for c in columns)
            body = []
            for r in rows[:200]:        # the UI is a summary, not a dump
                cells = "".join(
                    f"<td>{html.escape(str(r.get(c, '')))}</td>"
                    for c in columns)
                body.append(f"<tr>{cells}</tr>")
            more = (f"<p>… {len(rows) - 200} more (see the JSON API)</p>"
                    if len(rows) > 200 else "")
            return (f"<table><tr>{head}</tr>{''.join(body)}</table>{more}")

        def kv(d: dict) -> str:
            return ", ".join(f"{html.escape(str(k))}={html.escape(str(v))}"
                             for k, v in sorted(d.items())) or "—"

        sections = [
            "<h1>ray_tpu dashboard</h1>",
            f"<p>{s['nodes']} nodes · {s['tasks']['total']} tasks "
            f"({kv(s['tasks']['by_state'])}) · "
            f"{s['actors']['total']} actors</p>",
            f"<p>cluster resources: {kv(s['cluster_resources'])}<br>"
            f"available: {kv(s['available_resources'])}</p>",
            f"<p>object store: {kv(s['store'])}</p>",
            "<h2>Nodes</h2>",
            table(nodes, ["node_id", "state", "row", "labels"]),
            "<h2>Actors</h2>",
            table(actors, ["actor_id", "name", "state", "pending_calls",
                           "inflight_calls"]),
            "<h2>Placement groups</h2>",
            table(pgs, ["placement_group_id", "state", "strategy",
                        "bundles"]),
        ]
        if self._jobs is not None:
            sections += ["<h2>Jobs</h2>",
                         table(s["jobs"],
                               ["job_id", "status", "entrypoint"])]
        try:
            from ..serve.router import request_plane_stats
            plane = request_plane_stats()
        except Exception:   # noqa: BLE001 — serve absent/unused
            plane = {}
        if plane:
            rows = [dict(v, deployment=k) for k, v in
                    sorted(plane.items())]
            sections += [
                "<h2>Serve request plane</h2>",
                table(rows, ["deployment", "replicas", "shards",
                             "inflight", "queued", "qps", "p50_ms",
                             "p99_ms", "shed", "expired",
                             "batch_size_mean"])]
            loans = getattr(self._cluster, "loans", None)
            if loans is not None:
                ls = loans.stats()
                sections.append(
                    f"<p>capacity loans: {ls['loans_active']} active · "
                    f"{ls['loans_total']} taken · "
                    f"{ls['reclaims_total']} reclaimed · "
                    f"{ls['loans_lost']} lost · last reclaim "
                    f"{ls['last_reclaim_latency_s']}s</p>")
        sections.append(
            '<p>APIs: <a href="/api/summary">summary</a> · '
            '<a href="/api/nodes">nodes</a> · '
            '<a href="/api/actors">actors</a> · '
            '<a href="/api/tasks">tasks</a> · '
            '<a href="/api/objects">objects</a> · '
            '<a href="/api/placement_groups">placement groups</a> · '
            '<a href="/api/serve">serve</a> · '
            '<a href="/api/versions">versions</a> · '
            '<a href="/api/leases">leases</a> · '
            '<a href="/api/broadcasts">broadcasts</a> · '
            '<a href="/api/health">health</a> · '
            '<a href="/api/stacks">stacks</a> · '
            '<a href="/api/timeline">timeline</a> · '
            '<a href="/api/jobs">jobs</a> · '
            '<a href="/metrics">metrics</a></p>')
        return ("<!doctype html><html><head>"
                '<meta http-equiv="refresh" content="5">'
                "<title>ray_tpu dashboard</title>"
                "<style>body{font-family:monospace;margin:2em}"
                "table{border-collapse:collapse}"
                "td,th{border:1px solid #999;padding:2px 8px;"
                "text-align:left}</style>"
                "</head><body>" + "".join(sections) + "</body></html>")
