"""Automatic failure detection: the GCS health-check manager.

Reference parity: ``GcsHealthCheckManager`` (``src/ray/gcs/gcs_server/
gcs_health_check_manager.cc``) pings every registered raylet on
``health_check_period_ms``; after ``health_check_failure_threshold``
consecutive missed checks the node is declared dead and drained
(SURVEY.md §5.3; mount empty).

In-process adaptation: a node is declared DEAD on structural failure —
its scheduling thread died or its worker pool is wiped out (all
processes dead and respawn broken) — for ``threshold`` consecutive
probes, then drained via ``cluster.remove_node``.  Event-loop
responsiveness (pong answered since our previous ping) and data-plane
reachability (an OPEN circuit breaker on the node's object-plane
address — see ``rpc/breaker.py``) are tracked and surfaced as
``suspect``, mirrored into the CRM so placement rounds soft-avoid the
node, but are deliberately NOT fatal: a loop
blocked 40 s in a first jit compile is indistinguishable in-process from
a wedged one, and upstream only gets hang-detection for free because a
hung raylet process also stops answering its RPC thread.  The head node
is monitored but never removed (its death is fatal upstream too — the
GCS lives there).
"""

from __future__ import annotations

import threading

from ..common.config import get_config
from ..common import clock as _clk


class HealthCheckManager:
    def __init__(self, cluster):
        cfg = get_config()
        self._cluster = cluster
        self._period = cfg.health_check_period_ms / 1000.0
        self._threshold = cfg.health_check_failure_threshold
        self._wake = threading.Event()
        self._stop = False
        self._thread: threading.Thread | None = None
        # NodeID -> {"misses": int, "pinged_at": float | None,
        #            "suspect": bool}
        self._state: dict = {}
        self.num_detected = 0
        self.num_quarantined = 0    # rows currently breaker-quarantined

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="health-check")
            self._thread.start()

    def shutdown(self) -> None:
        """Stop AND join: an in-flight round emitting events or removing
        nodes must not race cluster teardown (it could recreate the
        just-deleted session dir through the event log's lazy open)."""
        self._stop = True
        self._wake.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self._period)
            if self._stop:
                return
            try:
                self.check_once()
            except Exception:   # noqa: BLE001 — monitor must survive
                import traceback
                traceback.print_exc()

    def quarantined_rows(self) -> set[int]:
        """Rows whose object-plane address currently has an OPEN circuit
        breaker (computed live from the rpc breaker registry: repeated
        transfer failures to a node quarantine it here even while its
        control-plane vitals look fine — the classic gray failure)."""
        from ..rpc import breaker as _breaker
        open_addrs = _breaker.open_peers()
        if not open_addrs:
            return set()
        return {row for row, addr in self._cluster.planes.items()
                if addr is not None and addr in open_addrs}

    def suspect_nodes(self) -> list:
        """NodeIDs currently flagged suspect (loop-lag or quarantine)."""
        return [nid for nid, st in self._state.items() if st["suspect"]]

    def check_once(self) -> list:
        """One probe round.  Returns nodes declared dead this round
        (tests call this directly for determinism)."""
        cluster = self._cluster
        declared = []
        quarantined = self.quarantined_rows()
        self.num_quarantined = len(quarantined)
        for row, raylet in list(cluster.raylets.items()):
            nid = raylet.node_id
            st = self._state.setdefault(
                nid, {"misses": 0, "pinged_at": None, "suspect": False})
            vitals = raylet.health_vitals()
            st["suspect"] = (st["pinged_at"] is not None and
                            vitals["last_pong"] < st["pinged_at"]) or \
                row in quarantined
            # mirror into the CRM so scheduling rounds soft-avoid the
            # row (advisory: snapshot() never masks suspect nodes)
            cluster.crm.set_suspect(row, bool(st["suspect"]))
            if vitals["thread_alive"] and vitals["workers_alive"]:
                st["misses"] = 0
            else:
                st["misses"] += 1
                if st["misses"] >= self._threshold:
                    if row == cluster._head_row:
                        # head death is fatal upstream; keep flagging only
                        continue
                    self.num_detected += 1
                    declared.append(nid)
                    self._state.pop(nid, None)
                    cluster.events.emit(
                        "health", "node_declared_dead", node_row=row,
                        node_id=nid.hex(), misses=st["misses"])
                    try:
                        cluster.remove_node(nid)
                    except ValueError:
                        pass        # raced with a manual/autoscaler removal
                    continue
            st["pinged_at"] = _clk.monotonic()
            raylet.ping()
        # forget departed nodes
        live = {r.node_id for r in cluster.raylets.values()}
        for nid in [n for n in self._state if n not in live]:
            del self._state[nid]
        # serve-plane piggyback: router load digests fold onto the
        # gossip board on the same beat that carries node liveness (no
        # extra RPC), and the capacity-loan state machine advances —
        # including the node-death loss booking for LOANED rows
        try:
            from ..serve.gossip import fold_all
            fold_all()
        except Exception:   # noqa: BLE001 — gossip is best-effort
            pass
        loans = getattr(cluster, "loans", None)
        if loans is not None:
            try:
                loans.tick()
            except Exception:   # noqa: BLE001 — monitor must survive
                import traceback
                traceback.print_exc()
        return declared

    def stats(self) -> dict:
        return {"num_detected": self.num_detected,
                "num_monitored": len(self._state),
                "num_suspect": sum(s["suspect"]
                                   for s in self._state.values()),
                "num_quarantined": self.num_quarantined}
