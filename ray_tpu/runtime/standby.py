"""Hot-standby head: sub-heartbeat control-plane failover.

A follower process armed next to the primary head.  It tails nothing
in-band — the primary already persists the whole GCS metadata plane
(job table, KV incl. the lease-epoch journal, fn registry) to
``persist_path`` every 2 s — so the standby's job is *detection* and
*promotion*:

- **detection** — probe the primary's ``ping`` every
  ``standby_probe_interval_s``.  Agents that lose their head link cast
  head-down votes here (``NodeAgent._vote_standby``), so the quorum
  signal arrives within one RPC-close, not one probe period.
- **promotion** — after ``standby_probe_misses`` consecutive failed
  probes, or ONE failed probe plus at least one agent vote, re-probe
  once (split-brain guard: a vote from a partitioned agent must not
  promote over a live primary) and then boot a full :class:`HeadNode`
  on the primary's host:port from the persisted snapshot.

Outstanding leases survive the promotion: grant authority already
lives at the raylets (``ray_tpu/leasing/``), and the promoted head
restores the revocation-epoch journal from the snapshot's KV plane
(``AgentHub._restore_epochs``), so it never re-issues an epoch the
dead head revoked.  Agents running with ``--reconnect-timeout``
re-register through their retry loop and re-lease their classes on
the first sync.

State machine::

    STANDBY --probe ok--------------------------> STANDBY (reset)
    STANDBY --miss (n >= misses OR n>=1 + vote)--> CONFIRMING
    CONFIRMING --re-probe ok--------------------> STANDBY (reset)
    CONFIRMING --re-probe fails-----------------> PROMOTING
    PROMOTING --HeadNode up---------------------> PRIMARY (terminal)
    PROMOTING --bind/boot fails-----------------> STANDBY (retry)
"""

from __future__ import annotations

import logging
import threading

from ..common import clock as _clk

_LOG = logging.getLogger("ray_tpu.standby")

__all__ = ["StandbyHead"]


class StandbyHead:
    """Armed follower; becomes a :class:`HeadNode` on primary death."""

    def __init__(self, head_address: str, host: str = "127.0.0.1",
                 port: int = 0, persist_path: str | None = None,
                 resources: dict | None = None,
                 num_workers: int | None = None):
        from ..common.config import get_config
        from ..rpc import transport as _transport
        cfg = get_config()
        self._head_address = head_address
        self._persist_path = persist_path
        self._resources = resources
        self._num_workers = num_workers
        self._probe_interval = max(
            float(cfg.standby_probe_interval_s), 0.05)
        self._probe_misses = max(int(cfg.standby_probe_misses), 1)
        self._misses = 0
        self._votes: set[str] = set()
        self._first_miss_t: float | None = None
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self.role = "standby"
        self.promotions = 0
        self.failover_ms: list[float] = []
        self.head = None            # the promoted HeadNode, if any
        self.server = _transport.serve({
            "ping": lambda: "standby",
            "standby_vote": self._vote,
            "standby_status": self.status,
            "stop_daemon": self._stop_async,
        }, host=host, port=port).start()
        from ..leasing import register_stats
        register_stats("standby", self.status)
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="standby-probe")
        self._probe_thread.start()

    @property
    def address(self) -> str:
        return self.server.address

    # -- quorum input --------------------------------------------------------
    def _vote(self, voter: str = "") -> bool:
        """An agent lost its head link.  Votes count only against the
        CURRENT outage window — every successful probe clears them, so
        a stale vote from a flapping agent cannot promote later."""
        with self._lock:
            self._votes.add(str(voter) or f"anon{len(self._votes)}")
            if self._first_miss_t is None:
                self._first_miss_t = _clk.monotonic()
        return True

    def status(self) -> dict:
        with self._lock:
            return {"role": self.role, "promotions": self.promotions,
                    "failover_ms": list(self.failover_ms),
                    "probe_misses": self._misses,
                    "votes": len(self._votes),
                    "head_address": self._head_address}

    # -- detection -----------------------------------------------------------
    def _probe_once(self) -> bool:
        from ..rpc import transport as _transport
        client = None
        try:
            client = _transport.connect(self._head_address)
            return client.call(
                "ping", timeout=min(self._probe_interval * 2, 5.0)) \
                is not None
        except Exception:   # noqa: BLE001 — unreachable == miss
            return False
        finally:
            if client is not None:
                try:
                    client.close()
                except Exception:   # noqa: BLE001
                    pass

    def _probe_loop(self) -> None:
        while not self._stop_event.wait(self._probe_interval):
            if self.role != "standby":
                return
            ok = self._probe_once()
            with self._lock:
                if ok:
                    self._misses = 0
                    self._votes.clear()
                    self._first_miss_t = None
                    continue
                self._misses += 1
                if self._first_miss_t is None:
                    self._first_miss_t = _clk.monotonic()
                promote = self._misses >= self._probe_misses or \
                    (self._misses >= 1 and self._votes)
            if promote:
                # split-brain guard: one more probe — an agent vote
                # during an asymmetric partition that only isolates
                # some agents must not promote over a live primary
                if self._probe_once():
                    with self._lock:
                        self._misses = 0
                        self._votes.clear()
                        self._first_miss_t = None
                    continue
                if self._promote():
                    return

    # -- promotion -----------------------------------------------------------
    def _promote(self) -> bool:
        """Boot a full head on the primary's host:port from the
        persisted snapshot.  ``HeadNode.__init__`` restores the GCS
        plane and re-runs interrupted jobs once the control surface is
        up; agents find the SAME address through their reconnect
        loops, so no client reconfiguration is needed."""
        from .head import HeadNode
        host, _, port_s = self._head_address.rpartition(":")
        with self._lock:
            t0 = self._first_miss_t or _clk.monotonic()
        try:
            head = HeadNode(resources=self._resources,
                            num_workers=self._num_workers,
                            host=host or "127.0.0.1",
                            port=int(port_s),
                            persist_path=self._persist_path)
        except Exception:   # noqa: BLE001 — bind/boot failed (port
            # still draining, snapshot unreadable): stay standby, the
            # next probe window retries the whole decision
            _LOG.exception("standby promotion failed; re-arming")
            with self._lock:
                self._misses = 0
                self._votes.clear()
                self._first_miss_t = None
            return False
        ms = round((_clk.monotonic() - t0) * 1000.0, 1)
        with self._lock:
            self.head = head
            self.role = "primary"
            self.promotions += 1
            self.failover_ms.append(ms)
        _LOG.warning("standby promoted to primary at %s "
                     "(failover %.0f ms)", head.address, ms)
        return True

    # -- lifecycle -----------------------------------------------------------
    def wait_for_shutdown(self, timeout: float | None = None) -> bool:
        return self._stop_event.wait(timeout)

    def _stop_async(self) -> str:
        # delay past the reply flush, as head.py's stop_daemon does
        timer = threading.Timer(0.2, self.stop)
        timer.daemon = True
        timer.start()
        return "stopping"

    def stop(self) -> None:
        self._stop_event.set()
        from ..leasing import unregister_stats
        unregister_stats("standby")
        head = self.head
        if head is not None:
            try:
                head.stop()
            except Exception:   # noqa: BLE001 — best-effort teardown
                pass
        self.server.stop()
