"""Worker pool: spawns, leases, and monitors worker processes.

Reference parity: the raylet's ``WorkerPool`` (prestarted per-language
workers, ``PopWorker``/``PushWorker`` lease handout, crash detection via
socket disconnect — ``src/ray/raylet/worker_pool.cc``, SURVEY.md §1 layer 4;
mount empty).

Workers are spawned (not forked): the driver owns a live TPU/JAX runtime
whose threads and device handles must not leak into children; spawn also
lets us scrub the axon/TPU env so workers never contend for the chip.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
from collections import deque
from typing import Callable

from .worker import worker_main
from ..common import clock as _clk

# env vars that would make a spawned worker grab or re-register the TPU
_SCRUB_ENV = ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY")
_spawn_env_lock = threading.Lock()


class LocalSpawner:
    """Default transport: spawn worker processes on THIS machine over
    multiprocessing pipes.  The pool is parameterized over this seam so a
    remote node agent can supply workers on another machine while every
    piece of lease/env/death bookkeeping stays in the one pool
    (``runtime/node_agent.py``)."""

    def __init__(self):
        self._ctx = mp.get_context("spawn")

    def spawn(self, index: int, arena_path: str | None,
              env_payload: dict | None):
        """Returns ``(proc, conn)``, already started; ``proc`` must offer
        terminate/join/is_alive, ``conn`` send/recv/close."""
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        with _spawn_env_lock:
            saved = {k: os.environ.pop(k) for k in _SCRUB_ENV
                     if k in os.environ}
            # export THIS process's resolved config as RT_* env vars so
            # the spawned worker (fresh interpreter) rebuilds the same
            # Config — programmatic system_config overrides would
            # otherwise silently vanish at the process boundary
            cfg_saved = {}
            try:
                from ..common.config import get_config
                for key, val in get_config().to_dict().items():
                    env_key = "RT_" + key.upper()
                    cfg_saved[env_key] = os.environ.get(env_key)
                    os.environ[env_key] = str(val)
                proc = self._ctx.Process(
                    target=worker_main,
                    args=(child_conn, index, arena_path, env_payload),
                    daemon=True, name=f"rt-worker-{index}")
                proc.start()
            finally:
                for env_key, old in cfg_saved.items():
                    if old is None:
                        os.environ.pop(env_key, None)
                    else:
                        os.environ[env_key] = old
                os.environ.update(saved)
        child_conn.close()
        return proc, parent_conn

    def stop(self) -> None:
        pass


class WorkerHandle:
    def __init__(self, index: int, proc, conn):
        self.index = index
        self.proc = proc
        self.conn = conn
        self.send_lock = threading.Lock()   # scheduler + reader both send
        self.ready = False
        self.dead = False
        self.blocked = False                # inside a blocking get
        self.dedicated = False              # actor worker: never in idle set
        self.env_key = None                 # runtime-env cache key
        self.env_payload = None             # staged payload (respawn)
        self.leased_task = None             # task_id_bin while executing
        # executing a streaming generator: it can pause indefinitely on
        # consumer backpressure, so tasks must never pipeline behind it
        # (the consumer may be waiting on exactly the queued task)
        self.leased_streaming = False
        # pipelined lease: (TaskID, assign_time) entries committed to
        # this worker but NOT yet sent — recallable (blocked worker,
        # stale lease, death) until the exec frame ships.  Mutated under
        # the owning raylet's _cv.
        self.assigned: deque = deque()
        self.fn_cache: set[str] = set()
        # per-function execution counts (max_calls worker recycling)
        self.fn_calls: dict[str, int] = {}
        # FIFO of shm-pin batches for get replies in flight to this
        # worker; drained by its get_ack frames, or by death/drain
        # cleanup (which may run on another thread — hence the lock and
        # the no_more_pins latch that stops late appends).
        self.pending_get_pins: deque = deque()
        self.pin_lock = threading.Lock()
        self.no_more_pins = False

    def send(self, msg) -> bool:
        with self.send_lock:
            if self.dead:
                return False
            try:
                self.conn.send(msg)
                return True
            except (OSError, BrokenPipeError):
                self.dead = True
                return False


class WorkerPool:
    """Owns worker processes; routes their frames to the raylet."""

    def __init__(self, num_workers: int,
                 on_message: Callable[[WorkerHandle, tuple], None],
                 on_death: Callable[[WorkerHandle], None],
                 on_idle: Callable[[], None] | None = None,
                 arena_path: str | None = None,
                 spawner=None):
        self._num = num_workers
        self._on_message = on_message
        self._on_death = on_death
        self._on_idle = on_idle or (lambda: None)
        self._arena_path = arena_path
        self._spawner = spawner if spawner is not None else LocalSpawner()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._workers: list[WorkerHandle] = []
        self._idle: list[WorkerHandle] = []
        self._next_index = 0
        self._shutdown = False
        # env keys with a spawn in flight -> owning worker index (-1
        # while the claim predates its handle).  Ownership matters: a
        # death-respawn of a post-ready env worker runs OUTSIDE the
        # gate, and its ready must not release a gate a concurrent
        # ensure_env_worker spawn still holds
        self._env_spawning: dict = {}
        self.node_id_hex: str | None = None     # set by the raylet

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        for _ in range(self._num):
            self._spawn_one()

    def _spawn_one(self, dedicated: bool = False, env_key=None,
                   env_payload: dict | None = None) -> WorkerHandle | None:
        with self._lock:
            if self._shutdown:
                return None
            index = self._next_index
            self._next_index += 1
        proc, parent_conn = self._spawner.spawn(index, self._arena_path,
                                                env_payload)
        handle = WorkerHandle(index, proc, parent_conn)
        handle.dedicated = dedicated
        handle.env_key = env_key
        handle.env_payload = env_payload
        with self._lock:
            self._workers.append(handle)
            # an unowned gate claim (-1) for this key becomes ours; the
            # key stays gated until the worker signals READY (see
            # _reader) — releasing at proc.start() would let every
            # scheduler scan during the worker's multi-hundred-ms boot
            # fork yet another process
            if (not dedicated and env_key is not None
                    and self._env_spawning.get(env_key) == -1):
                self._env_spawning[env_key] = handle.index
        threading.Thread(target=self._reader, args=(handle,),
                         daemon=True, name=f"rt-reader-{index}").start()
        return handle

    def spawn_dedicated(self, env_key=None,
                        env_payload: dict | None = None) -> WorkerHandle:
        """Spawn a worker that is never leased from the idle set — the
        dedicated actor-worker model (reference: each actor gets its own
        worker process), optionally inside a staged runtime env."""
        handle = self._spawn_one(dedicated=True, env_key=env_key,
                                 env_payload=env_payload)
        if handle is None:
            raise RuntimeError("pool is shut down")
        return handle

    def ensure_env_worker(self, env_key, env_payload: dict) -> None:
        """Grow the per-env worker cache by one (single spawn in flight
        per key).  WHEN to grow is the raylet's call — a one-per-env
        cache deadlocks when tasks sharing an env block on each other (a
        barrier under a job-level runtime_env), while unconditional
        growth double-spawns on sequential reuse, so the raylet spawns
        immediately only on cold start and otherwise after a grace
        period (``env_worker_grace_ms``)."""
        with self._lock:
            if env_key in self._env_spawning:
                return
            self._env_spawning[env_key] = -1    # claimed; spawn next
        try:
            self._spawn_one(env_key=env_key, env_payload=env_payload)
        except Exception:
            # a failed fork must not wedge the gate: future scans retry
            with self._lock:
                if self._env_spawning.get(env_key) == -1:
                    del self._env_spawning[env_key]
            raise

    def live_env_workers(self, env_key) -> int:
        """Leasable workers staged into this env (idle or busy, not
        dedicated to an actor), plus any spawn in flight."""
        with self._lock:
            n = sum(1 for h in self._workers
                    if h.env_key == env_key and not h.dead
                    and not h.dedicated)
            if env_key in self._env_spawning:
                n += 1
            return n

    def _reader(self, handle: WorkerHandle) -> None:
        while True:
            try:
                msg = handle.conn.recv()
            except (EOFError, OSError):
                break
            if msg[0] == "ready":
                if self.node_id_hex:
                    # runtime-context identity: tell the worker which
                    # node hosts it (reference: RuntimeContext.node_id)
                    handle.send(("node_info", self.node_id_hex))
                if not handle.dedicated and handle.env_key is not None:
                    with self._lock:
                        # boot done: reopen the env gate — but only OUR
                        # claim; a death-respawn's ready must not free a
                        # gate a concurrent ensure spawn still holds
                        if self._env_spawning.get(handle.env_key) \
                                == handle.index:
                            del self._env_spawning[handle.env_key]
                with self._cv:
                    handle.ready = True
                    if not handle.dedicated:
                        self._idle.append(handle)
                    self._cv.notify_all()
                if not handle.dedicated:
                    self._on_idle()
                continue
            try:
                self._on_message(handle, msg)
            except Exception:  # noqa: BLE001 — a bad frame must not kill
                import traceback
                traceback.print_exc()
        handle.dead = True
        with self._cv:
            if handle in self._idle:
                self._idle.remove(handle)
            self._cv.notify_all()
        if not self._shutdown:
            self._on_death(handle)
            if not handle.dedicated:
                # keep the pool at strength; env workers respawn into
                # their staged environment.  A worker that died MID-BOOT
                # still owns its gate claim: hand the claim to the
                # replacement (back to -1, which the respawned _spawn_one
                # re-claims) so the gate reopens at the replacement's
                # ready — or here, on spawn failure
                if handle.env_key is not None:
                    with self._lock:
                        if self._env_spawning.get(handle.env_key) \
                                == handle.index:
                            self._env_spawning[handle.env_key] = -1
                try:
                    self._spawn_one(env_key=handle.env_key,
                                    env_payload=handle.env_payload)
                except Exception:
                    if handle.env_key is not None:
                        with self._lock:
                            if self._env_spawning.get(handle.env_key) \
                                    == -1:
                                del self._env_spawning[handle.env_key]
                    raise

    # -- leasing ------------------------------------------------------------
    def pop_idle(self, env_key=None) -> WorkerHandle | None:
        """Lease an idle worker whose runtime env matches ``env_key``
        (None = the default environment)."""
        with self._cv:
            for i in range(len(self._idle) - 1, -1, -1):
                h = self._idle[i]
                if h.dead:
                    del self._idle[i]
                    continue
                if h.env_key == env_key:
                    del self._idle[i]
                    return h
            return None

    def pipeline_target(self, env_key=None,
                        depth: int = 2) -> WorkerHandle | None:
        """A busy (executing, not blocked, not dedicated) worker with
        room in its pipelined-lease queue, matching ``env_key`` —
        least-loaded first.  ``assigned`` lengths are read without the
        raylet lock (heuristic tie-break only; the raylet re-checks
        under its own lock when committing)."""
        with self._cv:
            best = None
            for h in self._workers:
                if h.dead or h.dedicated or h.blocked or \
                        h.leased_streaming or \
                        h.env_key != env_key or h.leased_task is None:
                    continue
                if len(h.assigned) >= depth - 1:
                    continue
                if best is None or len(h.assigned) < len(best.assigned):
                    best = h
            return best

    def release(self, handle: WorkerHandle) -> None:
        with self._cv:
            handle.leased_task = None
            handle.leased_streaming = False
            if not handle.dead and handle not in self._idle:
                self._idle.append(handle)
                self._cv.notify_all()
        self._on_idle()

    def wait_ready(self, count: int = 1, timeout: float = 60.0) -> bool:
        """Block until at least ``count`` workers signalled ready."""
        deadline = _clk.monotonic() + timeout
        with self._cv:
            while sum(h.ready and not h.dead for h in self._workers) < count:
                remaining = deadline - _clk.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def num_alive(self) -> int:
        with self._lock:
            return sum(not h.dead for h in self._workers)

    def expected(self) -> int:
        """Configured steady-state pool size (health checks compare
        num_alive against this)."""
        return self._num

    def grow_for_blocked(self, max_factor: int = 4) -> bool:
        """Spawn one extra DEFAULT worker when the pool is starved by
        workers parked in a blocking get (reference: workers blocked in
        ray.get stop counting toward the soft limit, and the pool starts
        replacements on demand — SURVEY §3.2 lease notes).  Env workers
        are excluded from every count here: an idle env worker cannot be
        leased by a default task (pop_idle is env-keyed), so it must not
        suppress growth, and env-cache growth has its own demand-driven
        path (``ensure_env_worker``)."""
        with self._lock:
            alive = [h for h in self._workers
                     if not h.dead and not h.dedicated
                     and h.env_key is None]
            unblocked = sum(not h.blocked for h in alive)
            idle_default = any(not h.dead and h.env_key is None
                               for h in self._idle)
            if idle_default or unblocked >= self._num \
                    or len(alive) >= self._num * max_factor:
                return False
        self._spawn_one()
        return True

    def kill_worker(self, handle: WorkerHandle) -> None:
        """Force-kill (ray.cancel(force=True) / ray.kill path)."""
        handle.dead = True
        try:
            handle.proc.terminate()
        except Exception:
            pass

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            workers = list(self._workers)
        for h in workers:
            h.send(("shutdown",))
        for h in workers:
            h.proc.join(timeout=2.0)
            if h.proc.is_alive():
                h.proc.terminate()
        for h in workers:
            try:
                h.conn.close()
            except Exception:
                pass
        self._spawner.stop()
