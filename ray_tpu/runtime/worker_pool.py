"""Worker pool: spawns, leases, and monitors worker processes.

Reference parity: the raylet's ``WorkerPool`` (prestarted per-language
workers, ``PopWorker``/``PushWorker`` lease handout, crash detection via
socket disconnect — ``src/ray/raylet/worker_pool.cc``, SURVEY.md §1 layer 4;
mount empty).

Workers are spawned (not forked): the driver owns a live TPU/JAX runtime
whose threads and device handles must not leak into children; spawn also
lets us scrub the axon/TPU env so workers never contend for the chip.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
from typing import Callable

from .worker import worker_main

# env vars that would make a spawned worker grab or re-register the TPU
_SCRUB_ENV = ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY")
_spawn_env_lock = threading.Lock()


class WorkerHandle:
    def __init__(self, index: int, proc, conn):
        self.index = index
        self.proc = proc
        self.conn = conn
        self.send_lock = threading.Lock()   # scheduler + reader both send
        self.ready = False
        self.dead = False
        self.blocked = False                # inside a blocking get
        self.dedicated = False              # actor worker: never in idle set
        self.leased_task = None             # task_id_bin while executing
        self.fn_cache: set[str] = set()
        # FIFO of shm-pin batches for get replies in flight to this
        # worker; drained by its get_ack frames, or by death/drain
        # cleanup (which may run on another thread — hence the lock and
        # the no_more_pins latch that stops late appends).
        from collections import deque
        self.pending_get_pins: deque = deque()
        self.pin_lock = threading.Lock()
        self.no_more_pins = False

    def send(self, msg) -> bool:
        with self.send_lock:
            if self.dead:
                return False
            try:
                self.conn.send(msg)
                return True
            except (OSError, BrokenPipeError):
                self.dead = True
                return False


class WorkerPool:
    """Owns worker processes; routes their frames to the raylet."""

    def __init__(self, num_workers: int,
                 on_message: Callable[[WorkerHandle, tuple], None],
                 on_death: Callable[[WorkerHandle], None],
                 on_idle: Callable[[], None] | None = None,
                 arena_path: str | None = None):
        self._num = num_workers
        self._on_message = on_message
        self._on_death = on_death
        self._on_idle = on_idle or (lambda: None)
        self._arena_path = arena_path
        self._ctx = mp.get_context("spawn")
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._workers: list[WorkerHandle] = []
        self._idle: list[WorkerHandle] = []
        self._next_index = 0
        self._shutdown = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        for _ in range(self._num):
            self._spawn_one()

    def _spawn_one(self, dedicated: bool = False) -> WorkerHandle | None:
        with self._lock:
            if self._shutdown:
                return None
            index = self._next_index
            self._next_index += 1
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        with _spawn_env_lock:
            saved = {k: os.environ.pop(k) for k in _SCRUB_ENV
                     if k in os.environ}
            try:
                proc = self._ctx.Process(
                    target=worker_main,
                    args=(child_conn, index, self._arena_path),
                    daemon=True, name=f"rt-worker-{index}")
                proc.start()
            finally:
                os.environ.update(saved)
        child_conn.close()
        handle = WorkerHandle(index, proc, parent_conn)
        handle.dedicated = dedicated
        with self._lock:
            self._workers.append(handle)
        threading.Thread(target=self._reader, args=(handle,),
                         daemon=True, name=f"rt-reader-{index}").start()
        return handle

    def spawn_dedicated(self) -> WorkerHandle:
        """Spawn a worker that is never leased from the idle set — the
        dedicated actor-worker model (reference: each actor gets its own
        worker process)."""
        handle = self._spawn_one(dedicated=True)
        if handle is None:
            raise RuntimeError("pool is shut down")
        return handle

    def _reader(self, handle: WorkerHandle) -> None:
        while True:
            try:
                msg = handle.conn.recv()
            except (EOFError, OSError):
                break
            if msg[0] == "ready":
                with self._cv:
                    handle.ready = True
                    if not handle.dedicated:
                        self._idle.append(handle)
                    self._cv.notify_all()
                if not handle.dedicated:
                    self._on_idle()
                continue
            try:
                self._on_message(handle, msg)
            except Exception:  # noqa: BLE001 — a bad frame must not kill
                import traceback
                traceback.print_exc()
        handle.dead = True
        with self._cv:
            if handle in self._idle:
                self._idle.remove(handle)
            self._cv.notify_all()
        if not self._shutdown:
            self._on_death(handle)
            if not handle.dedicated:
                self._spawn_one()           # keep the task pool at strength

    # -- leasing ------------------------------------------------------------
    def pop_idle(self) -> WorkerHandle | None:
        with self._cv:
            while self._idle:
                h = self._idle.pop()
                if not h.dead:
                    return h
            return None

    def release(self, handle: WorkerHandle) -> None:
        with self._cv:
            handle.leased_task = None
            if not handle.dead and handle not in self._idle:
                self._idle.append(handle)
                self._cv.notify_all()
        self._on_idle()

    def wait_ready(self, count: int = 1, timeout: float = 60.0) -> bool:
        """Block until at least ``count`` workers signalled ready."""
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while sum(h.ready and not h.dead for h in self._workers) < count:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def num_alive(self) -> int:
        with self._lock:
            return sum(not h.dead for h in self._workers)

    def expected(self) -> int:
        """Configured steady-state pool size (health checks compare
        num_alive against this)."""
        return self._num

    def grow_for_blocked(self, max_factor: int = 4) -> bool:
        """Spawn one extra worker when the pool is starved by workers
        parked in a blocking get (reference: workers blocked in ray.get
        stop counting toward the soft limit, and the pool starts
        replacements on demand — SURVEY §3.2 lease notes)."""
        with self._lock:
            alive = [h for h in self._workers
                     if not h.dead and not h.dedicated]
            unblocked = sum(not h.blocked for h in alive)
            if self._idle or unblocked >= self._num \
                    or len(alive) >= self._num * max_factor:
                return False
        self._spawn_one()
        return True

    def kill_worker(self, handle: WorkerHandle) -> None:
        """Force-kill (ray.cancel(force=True) / ray.kill path)."""
        handle.dead = True
        try:
            handle.proc.terminate()
        except Exception:
            pass

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            workers = list(self._workers)
        for h in workers:
            h.send(("shutdown",))
        for h in workers:
            h.proc.join(timeout=2.0)
            if h.proc.is_alive():
                h.proc.terminate()
        for h in workers:
            try:
                h.conn.close()
            except Exception:
                pass
