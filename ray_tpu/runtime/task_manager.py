"""Owner-side task bookkeeping: lifetimes, retries, completion, lineage.

Reference parity: the core worker's ``TaskManager`` (retry budget and
completion accounting for submitted tasks) plus its lineage pinning —
completed specs are retained for object reconstruction until the
``lineage_pinning_memory_mb`` budget evicts them oldest-first, and a
record is released early once every return object has gone out of scope
(``src/ray/core_worker/task_manager.cc``, SURVEY.md §1 layer 7, §5.3/§5.4;
mount empty).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from ..common.config import get_config
from ..common.ids import ObjectID, TaskID
from ..common.task_spec import TaskSpec


@dataclass
class TaskRecord:
    spec: TaskSpec
    retries_left: int
    return_ids: list[ObjectID]
    done: bool = False
    recovering: bool = False        # a reconstruction resubmit is in flight
    lineage_bytes: int = 0          # retained-spec cost while done
    dead_returns: set = field(default_factory=set)


class TaskManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._records: dict[TaskID, TaskRecord] = {}
        # completed records in retention order (lineage eviction is FIFO:
        # oldest finished task loses reconstructability first)
        self._done: "OrderedDict[TaskID, TaskRecord]" = OrderedDict()
        self._lineage_bytes = 0
        self._budget = get_config().lineage_pinning_memory_mb * (1 << 20)
        self.lineage_evictions = 0

    def register(self, spec: TaskSpec) -> TaskRecord:
        return_ids = [ObjectID.for_task_return(spec.task_id, i + 1)
                      for i in range(spec.num_returns)]
        rec = TaskRecord(spec, spec.max_retries, return_ids)
        with self._lock:
            self._records[spec.task_id] = rec
        return rec

    def list_rows(self) -> list[dict]:
        """State-API rows for every live record (pending/running +
        lineage-retained finished) — keeps the storage layout private."""
        with self._lock:
            records = list(self._records.items()) + \
                list(self._done.items())
        rows, seen = [], set()
        for tid, rec in records:
            if tid in seen:
                continue
            seen.add(tid)
            rows.append({
                "task_id": tid.hex(),
                "name": rec.spec.function_descriptor,
                "state": "FINISHED" if rec.done
                else "PENDING_OR_RUNNING",
                "num_returns": rec.spec.num_returns,
                "retries_left": rec.retries_left,
                "resources": rec.spec.resources.to_dict()})
        return rows

    def get(self, task_id: TaskID) -> TaskRecord | None:
        with self._lock:
            return self._records.get(task_id)

    def complete(self, task_id: TaskID) -> TaskRecord | None:
        """Mark done and move the record into the lineage retention window
        (sized by ``lineage_pinning_memory_mb``); evicted records lose
        reconstructability, and their specs' strong references to argument
        ObjectRefs drop (the refcount cascade)."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None:
                return None
            if rec.done:                # double-completion (cancel races a
                return rec              # late result): already accounted
            rec.done = True
            rec.recovering = False
            if not rec.lineage_bytes:
                # never dispatched (failed pre-dispatch): flat floor — the
                # dispatch path stamps the real serialized size
                rec.lineage_bytes = 256
            if rec.dead_returns.issuperset(rec.return_ids):
                # nothing downstream can ever need this lineage
                del self._records[task_id]
                return rec
            self._done[task_id] = rec
            self._lineage_bytes += rec.lineage_bytes
            self._evict_over_budget_locked()
            return rec

    def _evict_over_budget_locked(self) -> None:
        while self._lineage_bytes > self._budget and self._done:
            tid, rec = self._done.popitem(last=False)
            self._lineage_bytes -= rec.lineage_bytes
            self._records.pop(tid, None)
            self.lineage_evictions += 1

    def on_return_reclaimed(self, object_id: ObjectID) -> None:
        """A return object went out of scope cluster-wide: once ALL of a
        finished task's returns are dead its lineage is released (nothing
        can ask for reconstruction — reference: lineage release on
        out-of-scope, SURVEY §5.3)."""
        tid = object_id.task_id()
        with self._lock:
            rec = self._records.get(tid)
            if rec is None:
                return
            rec.dead_returns.add(object_id)
            if rec.done and rec.dead_returns.issuperset(rec.return_ids):
                del self._records[tid]
                if self._done.pop(tid, None) is not None:
                    self._lineage_bytes -= rec.lineage_bytes

    def mark_reconstructing(self, task_id: TaskID) -> bool:
        """Claim a record for a reconstruction resubmit.  Consumes one
        retry; False when already in flight (dedupe), unknown, evicted, or
        out of retries."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None:
                return False
            if rec.recovering or not rec.done:
                return True     # a resubmit (or first run) is in flight
            if rec.retries_left <= 0:
                return False
            rec.retries_left -= 1
            rec.spec.attempt_number += 1
            rec.done = False
            rec.recovering = True
            if self._done.pop(task_id, None) is not None:
                self._lineage_bytes -= rec.lineage_bytes
            # dead_returns is kept: already-reclaimed returns must NOT be
            # re-sealed by the reconstruction (a re-sealed dead return has
            # no refs and no pending decref — it would never be reclaimed)
            return True

    def should_retry(self, task_id: TaskID) -> bool:
        """Consume one retry if any remain (worker-crash path)."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None or rec.done or rec.retries_left <= 0:
                return False
            rec.retries_left -= 1
            rec.spec.attempt_number += 1
            return True

    def pending_count(self) -> int:
        with self._lock:
            return sum(not r.done for r in self._records.values())

    def stats(self) -> dict:
        with self._lock:
            return {"num_records": len(self._records),
                    "num_done_retained": len(self._done),
                    "lineage_bytes": self._lineage_bytes,
                    "lineage_evictions": self.lineage_evictions}
