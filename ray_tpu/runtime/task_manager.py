"""Owner-side task bookkeeping: lifetimes, retries, completion.

Reference parity: the core worker's ``TaskManager`` (retry budget and
completion accounting for submitted tasks) — ``src/ray/core_worker/
task_manager.cc``, SURVEY.md §1 layer 7; mount empty.  Lineage pinning for
reconstruction builds on the ``specs`` this manager retains.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..common.ids import ObjectID, TaskID
from ..common.task_spec import TaskSpec


@dataclass
class TaskRecord:
    spec: TaskSpec
    retries_left: int
    return_ids: list[ObjectID]
    done: bool = False


class TaskManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._records: dict[TaskID, TaskRecord] = {}

    def register(self, spec: TaskSpec) -> TaskRecord:
        return_ids = [ObjectID.for_task_return(spec.task_id, i + 1)
                      for i in range(spec.num_returns)]
        rec = TaskRecord(spec, spec.max_retries, return_ids)
        with self._lock:
            self._records[spec.task_id] = rec
        return rec

    def get(self, task_id: TaskID) -> TaskRecord | None:
        with self._lock:
            return self._records.get(task_id)

    def complete(self, task_id: TaskID) -> TaskRecord | None:
        with self._lock:
            rec = self._records.get(task_id)
            if rec is not None:
                rec.done = True
            return rec

    def should_retry(self, task_id: TaskID) -> bool:
        """Consume one retry if any remain (worker-crash path)."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None or rec.done or rec.retries_left <= 0:
                return False
            rec.retries_left -= 1
            rec.spec.attempt_number += 1
            return True

    def pending_count(self) -> int:
        with self._lock:
            return sum(not r.done for r in self._records.values())

    def pop_finished(self, keep_lineage: bool = True) -> None:
        """Drop completed records (lineage pinning keeps them by default
        until the reconstruction budget evicts — SURVEY §5.3/§5.4)."""
        if keep_lineage:
            return
        with self._lock:
            for tid in [t for t, r in self._records.items() if r.done]:
                del self._records[tid]
