"""Owner-side task bookkeeping: lifetimes, retries, completion, lineage.

Reference parity: the core worker's ``TaskManager`` (retry budget and
completion accounting for submitted tasks) plus its lineage pinning —
completed specs are retained for object reconstruction until the
``lineage_pinning_memory_mb`` budget evicts them oldest-first, and a
record is released early once every return object has gone out of scope
(``src/ray/core_worker/task_manager.cc``, SURVEY.md §1 layer 7, §5.3/§5.4;
mount empty).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from ..common.config import get_config
from ..common.ids import ObjectID, TaskID
from ..common.task_spec import TaskSpec
from ..common import clock as _clk


@dataclass
class TaskRecord:
    spec: TaskSpec
    retries_left: int
    return_ids: list[ObjectID]
    done: bool = False
    recovering: bool = False        # a reconstruction resubmit is in flight
    lineage_bytes: int = 0          # retained-spec cost while done
    dead_returns: set = field(default_factory=set)


@dataclass
class StreamState:
    """Streaming-generator progress for one producing task or actor
    call (spec/call ``num_returns == -1``): highest sealed item index,
    whether the producer finished (and its error), and whether the
    consumer closed the stream.  Lives in its OWN table so actor calls
    (which have no TaskRecord) stream through the same machinery."""
    sealed: int = 0
    done: bool = False
    error: object = None
    closed: bool = False


class TaskManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._stream_cv = threading.Condition()     # stream progress
        self._streams: dict[TaskID, StreamState] = {}
        self._records: dict[TaskID, TaskRecord] = {}
        # completed records in retention order (lineage eviction is FIFO:
        # oldest finished task loses reconstructability first)
        self._done: "OrderedDict[TaskID, TaskRecord]" = OrderedDict()
        self._lineage_bytes = 0
        self._budget = get_config().lineage_pinning_memory_mb * (1 << 20)
        self.lineage_evictions = 0

    def register(self, spec: TaskSpec) -> TaskRecord:
        # streaming generators (num_returns == -1) have no fixed return
        # set: items seal incrementally as the generator yields
        n = max(spec.num_returns, 0)
        return_ids = [ObjectID.for_task_return(spec.task_id, i + 1)
                      for i in range(n)]
        rec = TaskRecord(spec, spec.max_retries, return_ids)
        with self._lock:
            self._records[spec.task_id] = rec
        if spec.num_returns == -1:
            self.stream_open(spec.task_id)
        return rec

    def list_rows(self) -> list[dict]:
        """State-API rows for every live record (pending/running +
        lineage-retained finished) — keeps the storage layout private."""
        with self._lock:
            records = list(self._records.items()) + \
                list(self._done.items())
        rows, seen = [], set()
        for tid, rec in records:
            if tid in seen:
                continue
            seen.add(tid)
            rows.append({
                "task_id": tid.hex(),
                "name": rec.spec.function_descriptor,
                "state": "FINISHED" if rec.done
                else "PENDING_OR_RUNNING",
                "num_returns": rec.spec.num_returns,
                "retries_left": rec.retries_left,
                "resources": rec.spec.resources.to_dict()})
        return rows

    def get(self, task_id: TaskID) -> TaskRecord | None:
        with self._lock:
            return self._records.get(task_id)

    def get_many(self, task_ids) -> list:
        """Batch record lookup: one lock round-trip for a whole
        placement beat's hand-off (the fused dispatch path).  Returns
        one record-or-None per id, in order."""
        with self._lock:
            return [self._records.get(t) for t in task_ids]

    def complete(self, task_id: TaskID) -> TaskRecord | None:
        """Mark done and move the record into the lineage retention window
        (sized by ``lineage_pinning_memory_mb``); evicted records lose
        reconstructability, and their specs' strong references to argument
        ObjectRefs drop (the refcount cascade)."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None:
                return None
            if rec.done:                # double-completion (cancel races a
                return rec              # late result): already accounted
            rec.done = True
            rec.recovering = False
            if not rec.lineage_bytes:
                # never dispatched (failed pre-dispatch): flat floor — the
                # dispatch path stamps the real serialized size
                rec.lineage_bytes = 256
            if rec.return_ids and \
                    rec.dead_returns.issuperset(rec.return_ids):
                # nothing downstream can ever need this lineage
                del self._records[task_id]
                return rec
            self._done[task_id] = rec
            self._lineage_bytes += rec.lineage_bytes
            self._evict_over_budget_locked()
            return rec

    def _evict_over_budget_locked(self) -> None:
        # records of OPEN streams are pinned: evicting one mid-iteration
        # would silently truncate the consumer's stream (wait_stream
        # reads unknown records as "ended")
        skipped = []
        while self._lineage_bytes > self._budget and self._done:
            tid, rec = self._done.popitem(last=False)
            if rec.spec.num_returns == -1 and \
                    self.stream_accepts(tid):
                skipped.append((tid, rec))
                continue
            self._lineage_bytes -= rec.lineage_bytes
            self._records.pop(tid, None)
            self.lineage_evictions += 1
        for tid, rec in reversed(skipped):
            self._done[tid] = rec
            self._done.move_to_end(tid, last=False)

    def on_return_reclaimed(self, object_id: ObjectID) -> None:
        """A return object went out of scope cluster-wide: once ALL of a
        finished task's returns are dead its lineage is released (nothing
        can ask for reconstruction — reference: lineage release on
        out-of-scope, SURVEY §5.3)."""
        tid = object_id.task_id()
        with self._lock:
            rec = self._records.get(tid)
            if rec is None:
                return
            rec.dead_returns.add(object_id)
            if rec.done and rec.return_ids and \
                    rec.dead_returns.issuperset(rec.return_ids):
                del self._records[tid]
                if self._done.pop(tid, None) is not None:
                    self._lineage_bytes -= rec.lineage_bytes

    def mark_reconstructing(self, task_id: TaskID) -> bool:
        """Claim a record for a reconstruction resubmit.  Consumes one
        retry; False when already in flight (dedupe), unknown, evicted, or
        out of retries."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None:
                return False
            if rec.recovering or not rec.done:
                return True     # a resubmit (or first run) is in flight
            if rec.retries_left <= 0:
                return False
            rec.retries_left -= 1
            rec.spec.attempt_number += 1
            rec.done = False
            rec.recovering = True
            if self._done.pop(task_id, None) is not None:
                self._lineage_bytes -= rec.lineage_bytes
            # dead_returns is kept: already-reclaimed returns must NOT be
            # re-sealed by the reconstruction (a re-sealed dead return has
            # no refs and no pending decref — it would never be reclaimed)
            return True

    def should_retry(self, task_id: TaskID) -> bool:
        """Consume one retry if any remain (worker-crash path)."""
        with self._lock:
            rec = self._records.get(task_id)
            if rec is None or rec.done or rec.retries_left <= 0:
                return False
            rec.retries_left -= 1
            rec.spec.attempt_number += 1
            return True

    # -- streaming generators -----------------------------------------------
    def stream_open(self, task_id: TaskID) -> None:
        """Register a stream at submission time: a consumer's wait on a
        never-opened (or fully finished+closed) stream reads as ended."""
        with self._stream_cv:
            self._streams.setdefault(task_id, StreamState())

    def stream_accepts(self, task_id: TaskID) -> bool:
        """May a produced item still seal?  False once the consumer
        closed the stream (or it was never opened / already reaped)."""
        with self._stream_cv:
            st = self._streams.get(task_id)
            return st is not None and not st.closed

    def stream_item_sealed(self, task_id: TaskID, index: int) -> None:
        """Item ``index`` (1-based) sealed.  Uses max() so a retrying
        re-execution's re-seals are idempotent."""
        with self._stream_cv:
            st = self._streams.get(task_id)
            if st is not None:
                st.sealed = max(st.sealed, index)
            self._stream_cv.notify_all()

    def stream_finished(self, task_id: TaskID, error=None) -> None:
        with self._stream_cv:
            st = self._streams.get(task_id)
            if st is not None:
                st.done = True
                if error is not None and st.error is None:
                    st.error = error
                if st.closed:
                    del self._streams[task_id]  # both sides finished
            self._stream_cv.notify_all()

    def wait_stream(self, task_id: TaskID, index: int,
                    timeout: float | None = None):
        """Block until item ``index+1`` exists or the stream finished.
        Returns (sealed, done, error, known); known=False means the
        stream was never opened or already reaped (closed + done) —
        consumers distinguish a one-shot stream consumed elsewhere from
        a legitimately empty one."""
        deadline = None if timeout is None else \
            _clk.monotonic() + timeout
        with self._stream_cv:
            while True:
                st = self._streams.get(task_id)
                if st is None:
                    return 0, True, None, False
                if st.sealed > index or st.done:
                    return st.sealed, st.done, st.error, True
                if deadline is not None:
                    remaining = deadline - _clk.monotonic()
                    if remaining <= 0:
                        return st.sealed, st.done, st.error, True
                    self._stream_cv.wait(remaining)
                else:
                    self._stream_cv.wait()

    def stream_abandon(self, task_id: TaskID, error) -> list:
        """Producer-side stall teardown: finish the stream with the
        error (RETAINING the state so a slow consumer's next wait sees
        a loud failure, not a clean end) and return every sealed item
        for reclamation — the payloads must not leak even though the
        error tombstone stays until the consumer closes."""
        with self._stream_cv:
            st = self._streams.get(task_id)
            if st is None:
                return []
            st.done = True
            if st.error is None:
                st.error = error
            orphans = [ObjectID.for_task_return(task_id, i)
                       for i in range(1, st.sealed + 1)]
            rec = self._records.get(task_id)
            if rec is not None:
                rec.dead_returns.update(orphans)
            self._stream_cv.notify_all()
        return orphans

    def stream_close(self, task_id: TaskID, consumed: int) -> list:
        """The consumer is done with a stream (exhausted it or abandoned
        it): unpin lineage eviction and return the ids of sealed-but-
        unconsumed items for the caller to reclaim.  Those ids also
        become dead returns (when a task record exists) so a producer
        retry cannot re-seal them."""
        with self._stream_cv:
            st = self._streams.get(task_id)
            if st is None:
                return []
            st.closed = True
            orphans = [ObjectID.for_task_return(task_id, i)
                       for i in range(consumed + 1, st.sealed + 1)]
            rec = self._records.get(task_id)
            if rec is not None:
                rec.dead_returns.update(orphans)
            if st.done:
                del self._streams[task_id]      # both sides finished
            self._stream_cv.notify_all()
        return orphans

    def pending_count(self) -> int:
        with self._lock:
            return sum(not r.done for r in self._records.values())

    def stats(self) -> dict:
        with self._lock:
            return {"num_records": len(self._records),
                    "num_done_retained": len(self._done),
                    "lineage_bytes": self._lineage_bytes,
                    "lineage_evictions": self.lineage_evictions}
