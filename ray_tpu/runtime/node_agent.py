"""Worker-node agent: joins a remote machine's workers to a head.

Reference parity: ``ray start --address=<head>`` boots a worker node
whose raylet registers with the GCS and leases local worker processes to
the cluster over gRPC, with a per-node plasma store and an object
manager moving payloads between nodes directly (``NodeManagerService`` +
``src/ray/object_manager/`` — SURVEY.md §1 layers 2-4,6, §3.1, §3.3;
mount empty).  The rebuild keeps ALL scheduling/lease/env state in the
head process (the head's ``WorkerPool`` and ``Raylet`` run unchanged)
and makes the process transport AND the data plane remote:

    head                                  agent machine
    ----                                  -------------
    Raylet ── WorkerPool ── AgentSpawner ──TCP── NodeAgent ── pipe ── worker
      │ (control frames: by-REFERENCE descriptors)   │ arena+store (plane)
      └── PullManager ──(op_pull: src → dest direct)─┘

- The **agent** (``NodeAgent``) spawns ``worker_main`` processes locally
  (same ``LocalSpawner`` mechanics as the head) and shuttles their pipe
  frames to/from the head over the RPC plane.  It owns a LOCAL object
  store (arena + spill dir): its workers read plasma args zero-copy from
  the agent's arena; big task results/puts seal into it and only their
  METADATA rides to the head (``result_x``/``put_x`` frames).  Payload
  bytes move between machines over the object plane
  (``runtime/object_plane.py``) — source to destination directly, never
  through the head.
- The **head** (``AgentHub`` + ``AgentSpawner``) serves the agent's
  registration, creates a normal raylet row whose pool spawns through
  the agent, and routes incoming worker frames to virtual pipe
  connections.  The raylet runs with a ``plane_address``: exec/get
  frames carry ``("r", oid)`` descriptors that the agent resolves
  against its own arena before handing them to the worker.

An agent disconnect (process death, network drop) surfaces through the
RPC client's ``on_close`` and drives the existing ``remove_node`` drain:
running tasks retry elsewhere, objects whose only copy lived on the
agent recover via lineage or surface ``ObjectLostError`` — exactly like
a node death.

Limitation (v1, noted): runtime-env ``working_dir``/``py_modules``
staging paths live on the head's filesystem, so tasks with those envs
only resolve on agents sharing that filesystem.
"""

from __future__ import annotations

import logging
import os
import queue
import shutil
import tempfile
import threading
import uuid
from collections import deque

from ..common.ids import NodeID, ObjectID, TaskID
from .worker_pool import LocalSpawner
from ..common import clock as _clk

_LOG = logging.getLogger("ray_tpu.node_agent")

_EOF = object()

# the head's add_node default — the register reply normally carries the
# effective resources, this is only the fallback for a headless boot
DEFAULT_NODE_RESOURCES = {"CPU": 2, "memory": 2}


def _lease_class_key(cu: dict) -> str:
    """Interned resource-class key: tasks with identical demand vectors
    share one leased budget (the repeat-class steady state the lease
    plane serves).  Shared by the agent's admission and the head's
    grant bookkeeping — both sides must intern identically."""
    return ",".join(f"{k}:{cu[k]}" for k in sorted(cu)) or "zero"


def _make_agent_arena(session_dir: str):
    """The agent machine's own arena (plasma analogue): /dev/shm when
    available, session dir otherwise — mirrors the head's
    ``cluster_utils._make_arena``, including reaping arenas left by
    crashed sessions (a SIGKILLed agent never runs ``_a_stop``; its
    /dev/shm file would otherwise leak RAM until reboot)."""
    from ..cluster_utils import reap_stale_arenas
    from ..common.config import get_config
    from ..native import Arena
    capacity = get_config().object_store_memory_mb * 1024 * 1024
    name = f"rt_arena_{os.getpid()}_{uuid.uuid4().hex[:8]}"
    try:
        reap_stale_arenas("/dev/shm")
        return Arena(os.path.join("/dev/shm", name), capacity, create=True)
    except OSError:
        return Arena(os.path.join(session_dir, name), capacity,
                     create=True)


# ---------------------------------------------------------------------------
# agent process side
# ---------------------------------------------------------------------------

class NodeAgent:
    """The daemon on a worker machine: spawn + relay + local object
    plane + AUTONOMOUS LOCAL DISPATCH.  Frame relay stays dumb except
    where the data plane demands resolution (by-reference descriptors)
    or extraction (big payloads seal locally; metadata rides up).

    Raylet-per-host (VERDICT r04 missing #2, SURVEY §7 step 8): a
    worker here submitting ``f.remote()`` no longer pays a head
    round-trip per lease.  The agent keeps a LOCAL availability view
    (seeded from the register reply's resources; head-dispatched execs
    carry their demand vector as a stripped 7th frame element) and an
    observed per-worker state (ready/inflight/dedicated/env).  An
    eligible nested submission — DEFAULT strategy, no runtime_env,
    non-streaming, function bytes known, all ObjectRef args resident
    in the LOCAL arena, resources available, an idle local worker —
    dispatches straight to that worker from the pump thread.  Nothing
    blocks on the head: ownership/lineage metadata folds up on a
    BATCHED ``agent_sync`` (started specs + done results + live local
    load), which the head registers into its TaskManager/refcounter so
    gets, retries, lineage recovery, and node-death drain behave
    exactly as for head-dispatched tasks (the head reconciles return
    refs on registration to close the fire-and-forget decref race).
    Ineligible submissions relay to the head unchanged — the head's
    global batch kernel IS the spillback path.  Local gets of
    locally-resident plasma objects are served from the agent arena
    the same way (no head round-trip).

    ``ray.cancel`` reaches agent-leased tasks: the head seals the
    cancellation and completes its record first (so any in-flight
    done/retry sync is skipped), then asks the agent over ``a_cancel``
    to drop the queued entry or force-kill the running worker.

    Known v1 limits, by design: a local worker death hands the task
    BACK to the head with a ``retry`` disposition rather than retrying
    in place; transient resource oversubscription between the head's
    CRM and the local view is bounded by the worker pool (the same
    class of slack as ``force_subtract``)."""

    def __init__(self, head_address: str,
                 resources: dict[str, float] | None = None,
                 num_workers: int = 2,
                 labels: dict[str, str] | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 reconnect_timeout_s: float = 0.0,
                 standby_address: str | None = None):
        """``reconnect_timeout_s`` > 0 makes the agent survive a head
        restart: on link loss it retries the head address for that long
        and re-registers as a fresh node (local workers of the dead
        head's pool are reaped, the local store resets — the restarted
        head has no directory entries for it).  ``standby_address``
        names a hot-standby head (``runtime/standby.py``): on link loss
        the agent casts a head-down vote there, the quorum input that
        lets the standby promote within one probe interval instead of
        waiting out its own miss threshold."""
        from ..rpc import transport as _transport
        from .object_plane import ObjectPlane
        from .object_store import MemoryStore
        self._head_address = head_address
        self._standby_address = standby_address
        self._resources = resources
        self._num_workers = num_workers
        self._labels = labels
        self._reconnect_timeout = reconnect_timeout_s
        self._spawner = LocalSpawner()
        self._workers: dict[int, tuple] = {}    # index -> (proc, conn)
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._stopping = False
        self._reconnecting = False
        # registration epoch: pump threads of a PREVIOUS head's workers
        # must not relay frames/EOFs to the re-registered head (their
        # indices collide with the new pool's)
        self._epoch = 0
        # local object plane: own arena + spill dir
        self._session_dir = tempfile.mkdtemp(prefix="ray_tpu_agent_")
        self._arena = _make_agent_arena(self._session_dir)
        self.store = MemoryStore(
            arena=self._arena,
            spill_dir=os.path.join(self._session_dir, "spill"))
        self.plane = ObjectPlane(self.store)
        # descriptor pins handed to local workers: exec pins release at
        # the task's result/error frame; get-reply pin batches at the
        # worker's get_ack (FIFO — the single-threaded worker acks in
        # receive order), everything at worker EOF
        self._exec_pins: dict[tuple[int, bytes], list] = {}
        self._get_pins: dict[int, deque] = {}
        self._pin_lock = threading.Lock()
        # -- autonomous local dispatch state --------------------------------
        self._fast_enabled = False      # head policy (register reply)
        self._draining = False          # node DRAINING: no new local leases
        self._policy_pushed = False     # an a_policy push wins over a
        #                                 concurrently-computed register
        #                                 reply (job env landing mid-
        #                                 registration)
        self._view_lock = threading.Lock()
        self._totals_cu: dict[str, int] = {}
        self._avail_cu: dict[str, int] = {}
        # index -> {"ready","dedicated","env","inflight","fns"}
        self._w_state: dict[int, dict] = {}
        self._send_locks: dict[int, threading.Lock] = {}
        self._fn_cache: dict[str, bytes] = {}
        self._fn_fetching: set[str] = set()     # in-flight head fetches
        self._fn_uploaded: set[str] = set()     # bytes shipped headward
        self._head_tasks: dict[bytes, tuple] = {}   # tid -> (cu, index)
        self._local_tasks: dict[bytes, dict] = {}   # agent-leased
        # accepted-but-undispatched local leases (FIFO, the raylet's
        # dispatch queue): drains as workers/resources free; entries
        # older than the lease timeout hand back to the head
        self._local_queue: deque = deque()
        self._LOCAL_QUEUE_CAP = 1024
        # small local-task results CACHE (authority stays at the head,
        # which got the bytes in the done-sync): serves local gets of
        # tiny results without a head round-trip.  LRU-bounded; a miss
        # just relays, and a stale entry can only duplicate bytes the
        # id still names (ids are never reused for different values)
        self._small_cache: "dict[bytes, bytes]" = {}
        self._small_cache_order: deque = deque()
        self._small_cache_bytes = 0
        self._small_cache_lock = threading.Lock()   # pump threads race
        self._SMALL_CACHE_CAP = 32 << 20
        self._sync_lock = threading.Lock()
        # ONE ordered batch of ("refs"|"started"|"done", ...) entries:
        # a single stream preserves every intra-agent ordering the
        # head's counter fold depends on (a parent's incref for a
        # child's return is enqueued before that child's done entry,
        # so the head folds them in that order too)
        self._sync_batch: list = []
        self._sync_wake = threading.Event()
        self._sync_thread: threading.Thread | None = None
        # -- lease plane (ray_tpu/leasing/): raylet-side grant authority.
        # Admission checks the epoch-stamped class budgets the head
        # leased to this node; a miss relays the submit to the head
        # (spillback) and rides the next sync as a lease request so the
        # rest of the fan-out fast-paths.  The fence horizon equals the
        # head's quiet-lease TTL: the node stops granting at or before
        # the moment the head revokes its epoch.
        from ..common.config import get_config
        cfg = get_config()
        self._lease = None
        self._lease_lock = threading.Lock()     # after _view_lock only
        self._lease_want: set[str] = set()
        self._last_sync_call = _clk.monotonic()
        self._msub_batches = 0
        self._msub_frames = 0
        self._msub_max = max(int(cfg.lease_submit_batch_max), 1)
        if cfg.lease_plane_enabled:
            from ..leasing import LocalLeaseCache, register_stats
            # capacity == the dispatch-queue bound: per-class budgets
            # (head-issued) are the binding admission limit; the
            # overcommit multiple of the queue cap is the backstop
            self._lease = LocalLeaseCache(
                capacity=self._LOCAL_QUEUE_CAP,
                fence_after_s=float(cfg.lease_ttl_s),
                overcommit=float(cfg.lease_overcommit),
                max_classes=int(cfg.lease_max_classes))
            self._lease.on_head_contact(_clk.monotonic())
            register_stats("agent", self._lease_stats)
        handlers = {
            "a_spawn": self._a_spawn,
            "a_send": self._a_send,
            "a_kill": self._a_kill,
            "a_stop": self._a_stop,
            "a_ping": lambda: "ok",
            "a_policy": self._a_policy,
            "a_cancel": self._a_cancel,
            "a_drain": self._a_drain,
        }
        handlers.update(self.plane.handlers())
        self.server = _transport.serve(handlers, host=host,
                                       port=port).start()
        self.plane.serve_address = self.server.address
        # head link: frames flow agent->head on this client; its loss
        # (head died) ends the agent — or, with reconnect enabled,
        # triggers the retry/re-register loop.  The INITIAL registration
        # retries under the same budget: a head dying mid-register must
        # not strand a reconnect-enabled agent
        deadline = _clk.monotonic() + max(reconnect_timeout_s, 0.0)
        self._reconnecting = True   # a mid-register drop must not fork
        try:                        # a racing reconnect loop
            while True:
                try:
                    # agent_fn (function-bytes fetch) is an idempotent
                    # read: let it ride out gray head links with retry
                    self._head = _transport.connect(head_address,
                                           on_close=self._on_head_lost,
                                           retryable=frozenset(
                                               {"agent_fn"}))
                    self.agent_id = NodeID.from_random().hex()
                    reply = self._head.call(
                        "agent_register", self.agent_id,
                        self.server.address, resources, num_workers,
                        labels, True, timeout=120.0)
                    self._apply_register_reply(reply, resources)
                    break
                except Exception:
                    if _clk.monotonic() >= deadline:
                        raise
                    with self._lock:    # epoch bump quiets stale pumps
                        self._epoch += 1
                        self._workers.clear()
                    _clk.sleep(1.0)
        finally:
            with self._lock:
                self._reconnecting = False
        self._sync_thread = threading.Thread(
            target=self._sync_loop, daemon=True, name="agent-sync")
        self._sync_thread.start()

    def _apply_register_reply(self, reply, resources) -> None:
        """Register reply: dict with the node's EFFECTIVE resources and
        the head's fast-path policy (a bare node-id hex from an older
        head keeps autonomy off)."""
        from ..common.resources import ResourceRequest
        if isinstance(reply, dict):
            self.node_id_hex = reply["node_id"]
            eff = reply.get("resources") or resources \
                or DEFAULT_NODE_RESOURCES
            fast = bool(reply.get("fast_path", False))
        else:
            self.node_id_hex = reply
            eff = resources or DEFAULT_NODE_RESOURCES
            fast = False
        cu = ResourceRequest(eff).cu()
        with self._view_lock:
            self._totals_cu = dict(cu)
            self._avail_cu = dict(cu)
        if not self._policy_pushed:
            # a push that raced in DURING registration is newer than
            # the reply's snapshot — don't overwrite it
            self._fast_enabled = fast
        lease = reply.get("lease") if isinstance(reply, dict) else None
        if self._lease is not None and lease is not None:
            with self._lease_lock:
                self._lease.on_head_contact(_clk.monotonic())
                epoch = int(lease.get("epoch", 0))
                self._lease.observe_epoch(epoch)
                self._lease.install(lease.get("grants") or {}, epoch)

    def _lease_stats(self) -> dict:
        """The node-side half of the observability satellite: lease
        cache counters + the pump's multi-submit batching counters."""
        s = self._lease.stats() if self._lease is not None else {}
        s["submit_batches"] = self._msub_batches
        s["submit_batched_frames"] = self._msub_frames
        return s

    def _lease_release(self, entry: dict) -> None:
        """A locally-admitted entry left the local system (done, error,
        handback): return its class admission.  Pop-once: every exit
        path may call this safely."""
        ck = entry.pop("lease_ck", None)
        if ck is not None and self._lease is not None:
            with self._lease_lock:
                self._lease.release(ck)

    def _a_policy(self, policy: dict) -> bool:
        """Head policy push (e.g. a job-level runtime_env appearing
        gates the env-blind fast path off)."""
        self._policy_pushed = True
        self._fast_enabled = bool(policy.get("fast_path", False))
        return True

    def _a_drain(self) -> int:
        """Node is DRAINING: stop leasing locally and hand every
        accepted-but-undispatched task back to the head for global
        placement ("requeue" — never ran, no retry consumed).  Tasks a
        worker is already RUNNING finish normally and report through
        the usual done-sync.  Returns how many were handed back."""
        self._draining = True
        with self._view_lock:
            handed = list(self._local_queue)
            self._local_queue.clear()
        for e in handed:
            self._finish_local(e, None, None, None, "requeue")
        return len(handed)

    # -- head failover -------------------------------------------------------
    def _vote_standby(self) -> None:
        """The head link dropped: cast a head-down vote at the hot
        standby (``runtime/standby.py``).  One agent vote plus the
        standby's own failed probe is enough to promote — sub-
        heartbeat failover instead of waiting out the miss threshold.
        Best-effort: no standby configured / reachable, no vote."""
        if not self._standby_address:
            return
        from ..rpc import transport as _transport
        try:
            c = _transport.connect(self._standby_address)
            try:
                c.call("standby_vote", getattr(self, "agent_id", ""),
                       timeout=5.0)
            finally:
                c.close()
        except Exception:   # noqa: BLE001 — standby gone too: the
            pass            # reconnect loop still covers recovery

    def _on_head_lost(self) -> None:
        if self._standby_address and not self._stopping:
            threading.Thread(target=self._vote_standby, daemon=True,
                             name="agent-standby-vote").start()
        if self._stopping or self._reconnect_timeout <= 0:
            self._stop_event.set()
            return
        with self._lock:
            if self._reconnecting:
                return      # one loop at a time: a client that drops
            self._reconnecting = True   # mid-register must not fork a
            #                             racing second registration
        threading.Thread(target=self._reconnect_loop, daemon=True,
                         name="agent-reconnect").start()

    def _reconnect_loop(self) -> None:
        """The head died: reap the dead pool's local workers, reset the
        local store (the restarted head has no directory rows for it),
        and re-register as a fresh node until the timeout lapses."""
        from ..rpc import transport as _transport
        deadline = _clk.monotonic() + self._reconnect_timeout
        # new epoch FIRST: surviving pump threads of the dead head's
        # workers go quiet instead of relaying colliding indices
        with self._lock:
            self._epoch += 1
            workers = list(self._workers.values())
            self._workers.clear()
        for proc, conn in workers:
            try:
                proc.terminate()
            except Exception:   # noqa: BLE001 — keep reaping the rest
                _LOG.debug("terminating stale worker failed",
                           exc_info=True)
        with self._pin_lock:
            self._exec_pins.clear()
            self._get_pins.clear()
        self._reset_autonomy_state()
        self.store.delete([oid for oid, _s, _k
                           in self.store.list_objects()])
        try:
            while _clk.monotonic() < deadline and not self._stopping:
                head = None
                try:
                    head = _transport.connect(self._head_address,
                                     on_close=self._on_head_lost,
                                     retryable=frozenset({"agent_fn"}))
                    # install the link BEFORE registering: the register
                    # call blocks on worker-ready frames, which the new
                    # pump threads relay through self._head/agent_id
                    self._head = head
                    self.agent_id = NodeID.from_random().hex()
                    reply = self._head.call(
                        "agent_register", self.agent_id,
                        self.server.address, self._resources,
                        self._num_workers, self._labels, True,
                        timeout=120.0)
                    self._apply_register_reply(reply, self._resources)
                    return      # rejoined
                except Exception:   # noqa: BLE001 — head still down
                    if head is not None:
                        head.close()
                    _clk.sleep(1.0)
            self._stop_event.set()
        finally:
            with self._lock:
                self._reconnecting = False

    def _reset_autonomy_state(self) -> None:
        """Head gone/replaced: agent-leased tasks can never report
        their done-sync — drop them (the head's drain fails/retries
        registered ones, exactly like node death)."""
        self._fast_enabled = False
        self._draining = False      # a fresh head re-decides the drain
        self._policy_pushed = False     # fresh head: fresh policy
        with self._sync_lock:
            self._sync_batch.clear()
        entries = list(self._local_tasks.values())
        self._local_tasks.clear()
        with self._view_lock:
            queued = list(self._local_queue)
            self._local_queue.clear()
            self._lease_want.clear()
        for e in entries:
            self.store.unpin(e["pins"])
        for e in entries:
            self._lease_release(e)
        for e in queued:
            self._lease_release(e)
        self._head_tasks.clear()
        self._fn_uploaded.clear()       # the new head has a fresh registry
        with self._small_cache_lock:
            self._small_cache.clear()
            self._small_cache_order.clear()
            self._small_cache_bytes = 0
        with self._view_lock:
            self._avail_cu = dict(self._totals_cu)
        self._w_state.clear()

    def wait_for_shutdown(self, timeout: float | None = None) -> bool:
        return self._stop_event.wait(timeout)

    def stop(self) -> None:
        self._stopping = True
        try:
            self._head.call("agent_bye", self.agent_id, timeout=5.0)
        except Exception:       # noqa: BLE001 — head may already be gone
            pass
        self._a_stop()

    # -- RPC handlers (called by the head) ----------------------------------
    def _a_spawn(self, index: int, env_payload: dict | None) -> int:
        """Spawn a local worker attached to the AGENT's arena; returns
        its real pid (0 = failed)."""
        proc, conn = self._spawner.spawn(index, self._arena.path,
                                         env_payload)
        with self._lock:
            self._workers[index] = (proc, conn)
            epoch = self._epoch
            self._send_locks.setdefault(index, threading.Lock())
            self._w_state[index] = {"ready": False, "dedicated": False,
                                    "env": env_payload is not None,
                                    "inflight": 0, "fns": set()}
        threading.Thread(target=self._pump, args=(index, conn, epoch),
                         daemon=True, name=f"agent-pump-{index}").start()
        return proc.pid or 0

    def _send_to_worker(self, index: int, msg) -> bool:
        """Serialized pipe write: head-relayed frames (``a_send``
        handler threads) and agent-local dispatch (pump threads) both
        target the same worker conn."""
        with self._lock:
            entry = self._workers.get(index)
            lock = self._send_locks.setdefault(index, threading.Lock())
        if entry is None:
            return False
        with lock:
            try:
                entry[1].send(msg)
                return True
            except (OSError, BrokenPipeError):
                return False

    def _a_send(self, index: int, msg) -> bool:
        with self._lock:
            entry = self._workers.get(index)
        if entry is None:
            return False
        original = msg
        try:
            msg = self._rewrite_down(index, msg)
            if msg is None:
                return True     # swallowed: the error frame went up
        except Exception:   # noqa: BLE001 — unexpected surgery failure:
            msg = original      # forward as-is; the worker surfaces an
            #                     unresolved-descriptor error, not a hang
        if self._send_to_worker(index, msg):
            return True
        self._release_frame_pins(index, msg)
        return False

    def _a_kill(self, index: int) -> None:
        with self._lock:
            entry = self._workers.get(index)
        if entry is not None:
            try:
                entry[0].terminate()
            except Exception:   # noqa: BLE001
                pass

    def _a_stop(self) -> str:
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for proc, conn in workers:
            try:
                conn.send(("shutdown",))
            except (OSError, BrokenPipeError):
                pass
        for proc, conn in workers:
            proc.join(timeout=2.0)
            if proc.is_alive():
                proc.terminate()
            try:
                conn.close()
            except Exception:   # noqa: BLE001
                pass
        self.plane.shutdown()
        try:
            self._arena.close()
        except Exception:       # noqa: BLE001
            pass
        if self._lease is not None:
            from ..leasing import unregister_stats
            unregister_stats("agent")
        shutil.rmtree(self._session_dir, ignore_errors=True)
        self._stop_event.set()
        return "stopping"

    # -- data-plane frame surgery -------------------------------------------
    def _rewrite_down(self, index: int, msg):
        """Head->worker: resolve by-reference descriptors against the
        LOCAL store (pin for the read's duration) and OBSERVE the
        frame stream for the autonomy state (fn cache, per-worker
        inflight, dedicated marking, resource debits).  Returns the
        frame to forward, or None to swallow it (resolution failure
        already sent an error frame up)."""
        kind = msg[0]
        state = self._w_state.get(index)
        if kind == "fn":
            self._fn_cache[msg[1]] = msg[2]
            if state is not None:
                state["fns"].add(msg[1])
        elif kind in ("actor_new", "actor_call"):
            if state is not None:
                state["dedicated"] = True
        elif kind == "exec":
            if len(msg) == 7:
                # plane frame: the head appended the task's demand cu
                # dict — strip it (workers know nothing of it) and
                # debit the local availability view until the result
                cu = msg[6]
                msg = msg[:6]
                if state is not None:
                    with self._lock:    # inflight is multi-thread RMW
                        state["inflight"] += 1
                self._head_tasks[msg[1]] = (cu, index)
                with self._view_lock:
                    for k, v in (cu or {}).items():
                        self._avail_cu[k] = self._avail_cu.get(k, 0) - v
            elif state is not None:
                with self._lock:
                    state["inflight"] += 1
        if kind == "exec" and len(msg) == 6 and msg[5]:
            extern, pins = [], []
            try:
                for d in msg[5]:
                    if d[0] == "r":
                        desc = self.store.descriptor_of(ObjectID(d[1]))
                        if desc[0] == "s":
                            pins.append((ObjectID(d[1]), desc[1]))
                        extern.append(desc)
                    else:
                        extern.append(d)
            except KeyError:
                self.store.unpin(pins)
                self._credit_head_task(msg[1])
                self._send_error_up(
                    index, msg[1],
                    "task arg is not resident on this node's object "
                    "plane (transfer failed or the object was freed)")
                return None
            if pins:
                with self._pin_lock:
                    self._exec_pins[(index, msg[1])] = pins
            return msg[:5] + (extern,)
        if kind == "get_reply_x" and msg[1] == "ok":
            descs, pins = [], []
            for d in msg[2]:
                if d[0] == "r":
                    try:
                        desc = self.store.descriptor_of(ObjectID(d[1]))
                    except KeyError:
                        from .object_store import ObjectLostError
                        from .serialization import RayTaskError, serialize
                        desc = ("vb", serialize(RayTaskError(
                            "get", "object vanished from the local "
                            "plane", ObjectLostError(d[1].hex()))))
                    if desc[0] == "s":
                        pins.append((ObjectID(d[1]), desc[1]))
                    descs.append(desc)
                else:
                    descs.append(d)
            if pins:
                with self._pin_lock:
                    self._get_pins.setdefault(index,
                                              deque()).append(pins)
            return (msg[0], msg[1], descs)
        return msg

    def _rewrite_up(self, index: int, msg):
        """Worker->head: big payloads seal into the LOCAL store and only
        metadata rides up; pin releases ride the task lifecycle.
        Returns None to SWALLOW a frame the agent fully handled
        (autonomous dispatch, locally-served gets)."""
        kind = msg[0]
        if kind == "ready":
            state = self._w_state.get(index)
            if state is not None:
                state["ready"] = True
            self._drain_local_queue()
        elif kind == "submit":
            # ("submit", spec_bytes, fn_id, fn_bytes): the autonomy
            # fast path — dispatch locally with NO head round-trip
            # when eligible; relay for global placement otherwise
            try:
                if self._try_local_dispatch(index, msg[1], msg[2],
                                            msg[3]):
                    return None
            except Exception:   # noqa: BLE001 — fast path must never
                pass            # drop a submission; fall through
            return msg
        elif kind == "refs":
            # coalesce ref-count batches into the sync stream instead
            # of one head call per flush: a tiny-task fan-out's pump
            # thread must not serialize on a head RTT between a refs
            # frame and the submit frame behind it
            with self._sync_lock:
                self._sync_batch.append(("refs", index, msg[1]))
            self._sync_wake.set()
            return None
        elif kind in ("result", "error") and msg[1] in self._local_tasks:
            try:
                self._on_local_done(index, msg)
            except Exception:   # noqa: BLE001 — a failed completion
                # hand the task back to the head as a retry rather
                # than losing it
                entry = self._local_tasks.pop(msg[1], None)
                if entry is not None:
                    self.store.unpin(entry["pins"])
                    self._finish_local(entry, None, None, None, "retry")
            return None
        elif kind == "get":
            served = self._try_local_get(index, msg)
            if served:
                return None
            return msg
        if kind in ("result", "actor_result"):
            if kind == "result":
                self._credit_head_task(msg[1])
            self._release_exec_pins(index, msg[1])
            tid = TaskID(msg[1])
            descs, any_big = [], False
            for i, data in enumerate(msg[2]):
                if len(data) > self.store._threshold:
                    oid = ObjectID.for_task_return(tid, i + 1)
                    self.store.put_serialized(oid, data)
                    k, size = self.store.plasma_info(oid)
                    if k in ("shm", "spill"):
                        descs.append(("p", oid.binary(), size))
                        any_big = True
                        continue
                    # store-full in-band fallback: bytes ride up
                descs.append(("v", data))
            if any_big:
                # trailing elements (contained-ref lists) pass through
                return (kind + "_x", msg[1], descs) + tuple(msg[3:])
            return msg
        if kind in ("error", "actor_error"):
            if kind == "error":
                self._credit_head_task(msg[1])
            self._release_exec_pins(index, msg[1])
            return msg
        if kind == "stream_item":
            # ("stream_item", tid, idx, payload, contained): big items
            # seal into the LOCAL arena; metadata rides up
            if len(msg[3]) > self.store._threshold:
                oid = ObjectID.for_task_return(TaskID(msg[1]), msg[2])
                self.store.put_serialized(oid, msg[3])
                k, size = self.store.plasma_info(oid)
                if k in ("shm", "spill"):
                    return ("stream_item_x", msg[1], msg[2],
                            ("p", oid.binary(), size), msg[4])
            return msg
        if kind == "put":
            if len(msg[2]) > self.store._threshold:
                oid = ObjectID(msg[1])
                self.store.put_serialized(oid, msg[2])
                k, size = self.store.plasma_info(oid)
                if k in ("shm", "spill"):
                    return ("put_x", msg[1], size) + tuple(msg[3:])
            return msg
        if kind == "get_ack":
            with self._pin_lock:
                dq = self._get_pins.get(index)
                batch = dq.popleft() if dq else None
            if batch:
                self.store.unpin(batch)
            # swallowed: the head never tracks pins for plane workers
            # (their s-descriptors are all resolved HERE), so the ack
            # is purely local — relaying it is a wasted head frame
            return None
        return msg

    def _send_error_up(self, index: int, task_id_bin: bytes,
                       message: str) -> None:
        from .serialization import RayTaskError, serialize
        try:
            self._head.call(
                "agent_frame", self.agent_id, index,
                ("error", task_id_bin,
                 serialize(RayTaskError("task", message))))
        except Exception:       # noqa: BLE001 — head gone
            pass

    def _release_exec_pins(self, index: int, task_id_bin: bytes) -> None:
        with self._pin_lock:
            pins = self._exec_pins.pop((index, task_id_bin), None)
        if pins:
            self.store.unpin(pins)

    def _credit_head_task(self, tid_bin: bytes) -> None:
        """A head-dispatched task finished (or will never run): return
        its demand to the local view, drop the worker's inflight."""
        entry = self._head_tasks.pop(tid_bin, None)
        if entry is None:
            return
        cu, index = entry
        with self._lock:
            state = self._w_state.get(index)
            if state is not None and state["inflight"] > 0:
                state["inflight"] -= 1
        with self._view_lock:
            for k, v in (cu or {}).items():
                self._avail_cu[k] = self._avail_cu.get(k, 0) + v
        self._drain_local_queue()       # a worker/resources just freed

    def _release_frame_pins(self, index: int, msg) -> None:
        """A rewritten frame failed to send: release the pins it carried
        (its ack/result will never come)."""
        kind = msg[0]
        if kind == "exec":
            self._release_exec_pins(index, msg[1])
            self._credit_head_task(msg[1])
        elif kind == "get_reply_x":
            with self._pin_lock:
                dq = self._get_pins.get(index)
                batch = dq.pop() if dq else None
            if batch:
                self.store.unpin(batch)

    def _release_index_pins(self, index: int) -> None:
        """Worker died/exited: every descriptor it held is dead."""
        with self._pin_lock:
            pins = []
            for key in [k for k in self._exec_pins if k[0] == index]:
                pins.extend(self._exec_pins.pop(key))
            for batch in self._get_pins.pop(index, ()):
                pins.extend(batch)
        if pins:
            self.store.unpin(pins)

    # -- autonomous local dispatch ------------------------------------------
    def _try_local_dispatch(self, submitter: int, spec_bytes: bytes,
                            fn_id: str, fn_bytes) -> bool:
        """ACCEPT a nested submission for local execution: eligible
        tasks enter the agent's FIFO dispatch queue (registered at the
        head via the started-sync) and drain as workers/resources
        free.  Returns True when the task was taken (the submit frame
        must then be swallowed); False relays it to the head for
        global placement."""
        if not self._fast_enabled or self._draining:
            return False
        sub = self._w_state.get(submitter)
        if sub is None or sub["env"] or sub["dedicated"]:
            # env/actor parents imply runtime-env inheritance the
            # agent cannot evaluate — the head merges those
            return False
        if fn_bytes is None and fn_id not in self._fn_cache:
            # a stub submission (bytes live only in the head's fn
            # registry): relay THIS one, but fetch the bytes in the
            # background so the rest of the fan-out fast-paths —
            # one head round-trip per function EVER, off every
            # dispatch path
            self._fetch_fn_async(fn_id)
            return False
        from ..common.task_spec import SchedulingStrategyKind
        from .object_ref import ObjectRef
        from .serialization import deserialize
        spec = deserialize(spec_bytes)
        if spec.strategy.kind is not SchedulingStrategyKind.DEFAULT \
                or spec.runtime_env or spec.num_returns < 0 \
                or getattr(spec, "max_calls", 0) > 0:
            # max_calls recycling is head-pool bookkeeping: relay
            return False
        from .object_store import PLASMA_KINDS
        for a in spec.args:
            if isinstance(a, ObjectRef):
                kind, _ = self.store.plasma_info(a.id)
                if kind not in PLASMA_KINDS:
                    return False    # not locally materializable
        cu = spec.resources.cu()
        with self._view_lock:
            if len(self._local_queue) >= self._LOCAL_QUEUE_CAP:
                return False
            for k, v in cu.items():
                if self._totals_cu.get(k, 0) < v:
                    return False    # infeasible here, ever
            ck = None
            if self._lease is not None:
                # the lease-plane admission proper: grant locally only
                # inside the epoch-stamped budget the head leased for
                # this class; a miss SPILLS BACK (the relayed submit
                # is the spillback) and requests the class on the next
                # sync so the rest of the fan-out fast-paths
                ck = _lease_class_key(cu)
                with self._lease_lock:
                    granted = self._lease.try_grant(ck,
                                                    _clk.monotonic())
                if not granted:
                    if len(self._lease_want) < 256:
                        self._lease_want.add(ck)
                    self._sync_wake.set()
                    return False
            entry = {"spec": spec, "spec_bytes": spec_bytes,
                     "fn_id": fn_id, "fn_bytes": fn_bytes,
                     "submitter": submitter, "cu": cu,
                     "lease_ck": ck, "enq": _clk.monotonic()}
            # started rides the sync BEFORE any dispatch: the result
            # can arrive arbitrarily fast, and its done entry must
            # never reach the head in a flush preceding registration.
            # Worker-defined functions' bytes ride along ONCE per fn —
            # the head's registry must stay complete (retries and
            # lineage reconstruction resolve fn_id there) even though
            # the submit frame that carried them was swallowed
            up_bytes = None
            if fn_bytes is not None and fn_id not in self._fn_uploaded:
                self._fn_uploaded.add(fn_id)
                up_bytes = fn_bytes
            with self._sync_lock:
                self._sync_batch.append(
                    ("started", spec_bytes, submitter, fn_id,
                     up_bytes))
            self._local_queue.append(entry)
        self._sync_wake.set()
        # the task is ACCEPTED from here on: a drain hiccup must not
        # unwind the accept (the caller would relay the submit and the
        # task would run twice) — the queue drains on the next trigger
        try:
            self._drain_local_queue()
        except Exception:   # noqa: BLE001
            pass
        return True

    def _a_cancel(self, tid_bin: bytes, force: bool) -> str:
        """Head-initiated cancel of an agent-leased task.  The head
        already sealed the cancellation error and completed the record
        — here we only stop the wasted work: drop a queued entry, or
        (force) kill the worker running it (its death handback finds
        the record done at the head and is skipped)."""
        with self._view_lock:
            for e in list(self._local_queue):
                if e["spec"].task_id.binary() == tid_bin:
                    self._local_queue.remove(e)
                    self._lease_release(e)
                    return "dequeued"
        entry = self._local_tasks.get(tid_bin)
        if entry is None:
            # dispatch window: the drain popped the queue entry but
            # has not inserted the running record yet — re-check once
            _clk.sleep(0.1)
            entry = self._local_tasks.get(tid_bin)
            if entry is None:
                return "unknown"
        if force:
            self._a_kill(entry["index"])
            return "killed"
        return "running"

    def _fetch_fn_async(self, fn_id: str) -> None:
        with self._lock:
            if fn_id in self._fn_fetching or fn_id in self._fn_cache:
                return
            self._fn_fetching.add(fn_id)

        def run() -> None:
            try:
                data = self._head.call("agent_fn", fn_id, timeout=30.0)
                if data is not None:
                    self._fn_cache[fn_id] = data
            except Exception:   # noqa: BLE001 — head gone/slow: the
                pass            # next stub submission retries
            finally:
                with self._lock:
                    self._fn_fetching.discard(fn_id)
        threading.Thread(target=run, daemon=True,
                         name=f"agent-fn-{fn_id[:8]}").start()

    def _drain_local_queue(self) -> None:
        """Dispatch queued local leases FIFO while a default worker is
        idle and the head entry's resources are available (strict FIFO:
        a head that cannot fit parks the queue, the same fairness the
        head raylet's class buckets give)."""
        while True:
            with self._view_lock:
                if not self._local_queue:
                    return
                entry = self._local_queue[0]
                cu = entry["cu"]
                for k, v in cu.items():
                    if self._avail_cu.get(k, 0) < v:
                        return
                windex = None
                with self._lock:
                    for i, st in self._w_state.items():
                        if st["ready"] and not st["dedicated"] \
                                and not st["env"] \
                                and st["inflight"] == 0 \
                                and i in self._workers:
                            windex = i
                            break
                    if windex is None:
                        return
                    self._w_state[windex]["inflight"] += 1
                for k, v in cu.items():
                    self._avail_cu[k] = self._avail_cu.get(k, 0) - v
                self._local_queue.popleft()
            try:
                ok = self._dispatch_now(entry, windex)
            except Exception:   # noqa: BLE001 — a failed dispatch must
                ok = False      # undo its lease, never leak it
            if not ok:
                # worker vanished / args freed: undo the lease and hand
                # the (already-registered) task back to the head —
                # "requeue" re-enters global scheduling without
                # consuming a retry attempt (the task never ran)
                with self._view_lock:
                    with self._lock:
                        st = self._w_state.get(windex)
                        if st is not None and st["inflight"] > 0:
                            st["inflight"] -= 1
                    for k, v in entry["cu"].items():
                        self._avail_cu[k] = self._avail_cu.get(k, 0) + v
                self._finish_local(entry, None, None, None, "requeue")

    def _dispatch_now(self, entry: dict, windex: int) -> bool:
        from .object_ref import ObjectRef
        from .serialization import serialize
        from .worker import ArgRef
        spec, fn_id = entry["spec"], entry["fn_id"]
        args, pins = [], []
        try:
            for a in spec.args:
                if isinstance(a, ObjectRef):
                    desc = self.store.descriptor_of(a.id)
                    if desc[0] == "s":
                        pins.append((a.id, desc[1]))
                    args.append(ArgRef(desc))
                else:
                    args.append(a)
        except KeyError:        # freed between accept and dispatch
            self.store.unpin(pins)
            return False
        state = self._w_state.get(windex)
        if state is not None and fn_id not in state["fns"]:
            data = entry["fn_bytes"] if entry["fn_bytes"] is not None \
                else self._fn_cache.get(fn_id)
            if data is None or not self._send_to_worker(
                    windex, ("fn", fn_id, data)):
                self.store.unpin(pins)
                return False
            state["fns"].add(fn_id)
            self._fn_cache.setdefault(fn_id, data)
        payload = serialize((tuple(args), spec.kwargs,
                             spec.num_returns))
        tid_bin = spec.task_id.binary()
        entry["index"] = windex
        entry["pins"] = pins
        self._local_tasks[tid_bin] = entry
        if not self._send_to_worker(
                windex, ("exec", tid_bin, fn_id, payload,
                         spec.trace_ctx, None)):
            self._local_tasks.pop(tid_bin, None)
            self.store.unpin(pins)
            return False
        return True

    def _on_local_done(self, index: int, msg) -> None:
        """Terminal frame of an agent-leased task: seal big returns
        into the LOCAL arena, queue the metadata for the batched
        done-sync, free the lease."""
        kind, tid_bin = msg[0], msg[1]
        entry = self._local_tasks.pop(tid_bin, None)
        if entry is None:
            return
        self.store.unpin(entry["pins"])
        with self._lock:
            st = self._w_state.get(entry["index"])
            if st is not None and st["inflight"] > 0:
                st["inflight"] -= 1
        with self._view_lock:
            for k, v in entry["cu"].items():
                self._avail_cu[k] = self._avail_cu.get(k, 0) + v
        if kind == "error":
            self._finish_local(entry, None, None, msg[2], "error")
            self._drain_local_queue()
            return
        try:
            tid = TaskID(tid_bin)
            descs = []
            for i, data in enumerate(msg[2]):
                oid = ObjectID.for_task_return(tid, i + 1)
                if len(data) > self.store._threshold:
                    self.store.put_serialized(oid, data)
                    k, size = self.store.plasma_info(oid)
                    if k in ("shm", "spill"):
                        descs.append(("p", oid.binary(), size))
                        continue
                self._small_cache_put(oid.binary(), data)
                descs.append(("v", data))
            self._finish_local(entry, descs,
                               msg[3] if len(msg) > 3 else None, None,
                               "done")
        except Exception:   # noqa: BLE001 — seal failure (arena+spill
            # exhausted, ...): the entry is already popped, so the
            # handback must happen HERE or the head record never
            # completes and the caller hangs
            self._finish_local(entry, None, None, None, "retry")
        self._drain_local_queue()

    def _finish_local(self, entry, descs, contained, err_bytes,
                      disposition: str) -> None:
        self._lease_release(entry)
        with self._sync_lock:
            self._sync_batch.append(
                ("done", entry["spec"].task_id.binary(), descs,
                 contained, err_bytes, disposition))
        self._sync_wake.set()

    def _on_worker_gone(self, index: int) -> None:
        """A local worker died/exited: hand its agent-leased tasks back
        to the head (retry disposition — the head's TaskManager owns
        the attempt budget) and credit its head-task debits."""
        lost = [tid for tid, e in list(self._local_tasks.items())
                if e["index"] == index]
        for tid_bin in lost:
            entry = self._local_tasks.pop(tid_bin, None)
            if entry is None:
                continue
            self.store.unpin(entry["pins"])
            with self._view_lock:
                for k, v in entry["cu"].items():
                    self._avail_cu[k] = self._avail_cu.get(k, 0) + v
            self._finish_local(entry, None, None, None, "retry")
        for tid_bin in [t for t, (_cu, i) in list(self._head_tasks.items())
                        if i == index]:
            self._credit_head_task(tid_bin)

    def _small_cache_put(self, oid_bin: bytes, data: bytes) -> None:
        if len(data) > self.store._threshold:
            return      # not small: the arena/spill already holds it
        with self._small_cache_lock:
            if oid_bin in self._small_cache:
                return
            self._small_cache[oid_bin] = data
            self._small_cache_order.append(oid_bin)
            self._small_cache_bytes += len(data)
            while self._small_cache_bytes > self._SMALL_CACHE_CAP and \
                    self._small_cache_order:
                old = self._small_cache_order.popleft()
                dropped = self._small_cache.pop(old, None)
                if dropped is not None:
                    self._small_cache_bytes -= len(dropped)

    def _try_local_get(self, index: int, msg) -> bool:
        """Serve a worker's get entirely from this machine when every
        requested object is plasma-resident in the local arena OR a
        cached small local-task result (the data is already here — a
        head round-trip would only copy the descriptor path)."""
        from .object_store import PLASMA_KINDS
        oids = [ObjectID(b) for b in msg[1]]
        if not oids:
            return False
        descs, pins = [], []
        try:
            for o in oids:
                small = self._small_cache.get(o.binary())
                if small is not None:
                    descs.append(("b", small))
                    continue
                kind, _ = self.store.plasma_info(o)
                if kind not in PLASMA_KINDS:
                    self.store.unpin(pins)
                    return False
                desc = self.store.descriptor_of(o)
                if desc[0] != "s":
                    self.store.unpin(pins)
                    return False
                pins.append((o, desc[1]))
                descs.append(desc)
        except KeyError:
            self.store.unpin(pins)
            return False
        if pins:
            # pin batches enter the FIFO only when the reply carries
            # "s" descriptors — the worker acks exactly those replies
            with self._pin_lock:
                self._get_pins.setdefault(index, deque()).append(pins)
        if not self._send_to_worker(index,
                                    ("get_reply_x", "ok", descs)):
            if pins:
                with self._pin_lock:
                    dq = self._get_pins.get(index)
                    if dq and dq[-1] is pins:
                        dq.pop()
                self.store.unpin(pins)
            return False
        return True

    # -- batched head sync ---------------------------------------------------
    def _sync_loop(self) -> None:
        """Ship started/done/load batches to the head: amortized (a
        2 ms coalescing window after the first append) so a fan-out of
        N local leases costs O(1) head frames, not O(N)."""
        while not self._stopping and not self._stop_event.is_set():
            if self._sync_wake.wait(timeout=0.5):
                _clk.sleep(0.002)       # coalesce a burst
                self._sync_wake.clear()
            # stale local leases (queued past the lease timeout behind
            # blocked/busy workers) spill back to the head for global
            # placement — the raylet's stale-lease spillback, agent-
            # side.  Runs on EVERY tick including wake timeouts: a
            # stranded queue with no further sync traffic is exactly
            # the case that must still spill
            from ..common.config import get_config
            stale_after = get_config().worker_lease_timeout_ms / 1000.0
            now = _clk.monotonic()
            stale = []
            with self._view_lock:
                while self._local_queue and \
                        now - self._local_queue[0]["enq"] > stale_after:
                    stale.append(self._local_queue.popleft())
            for e in stale:
                self._finish_local(e, None, None, None, "requeue")
            with self._sync_lock:
                batch = self._sync_batch
                self._sync_batch = []
            want = None
            if self._lease is not None:
                with self._view_lock:
                    if self._lease_want:
                        want = sorted(self._lease_want)
                        self._lease_want.clear()
                if want is None and not batch and \
                        now - self._last_sync_call > \
                        self._lease.fence_after_s / 3.0:
                    # lease keepalive: a fenced cache spills EVERYTHING,
                    # so an idle agent still confirms head contact well
                    # inside the fence horizon (and folds fresh
                    # grants/epochs while it's there)
                    want = []
            if not batch and want is None:
                continue
            load: dict[str, int] = {}
            for e in list(self._local_tasks.values()):
                for k, v in e["cu"].items():
                    load[k] = load.get(k, 0) + v
            try:
                reply = self._head.call("agent_sync", self.agent_id,
                                        batch, load, want)
                self._last_sync_call = _clk.monotonic()
                self._fold_sync_reply(reply)
            except Exception:   # noqa: BLE001 — head gone: the
                # on_close/reconnect flow owns cleanup; log so a sync
                # silently failing for OTHER reasons is visible
                _LOG.debug("agent_sync to head failed", exc_info=True)

    def _fold_sync_reply(self, reply) -> None:
        """Lease half of the sync reply: a confirmed head contact, the
        node's current epoch, and fresh grants.  An epoch ADVANCE means
        the head revoked this node's grant set (quiet lease / drain /
        re-admission): the head has already requeued everything it
        registered, so locally-queued not-yet-started grants hand back
        for global placement rather than running under a dead epoch."""
        if self._lease is None or not isinstance(reply, dict):
            return
        epoch = int(reply.get("epoch", 0))
        with self._lease_lock:
            self._lease.on_head_contact(_clk.monotonic())
            revoked = self._lease.observe_epoch(epoch)
            grants = reply.get("grants")
            if grants:
                self._lease.install(grants, epoch)
        if revoked:
            with self._view_lock:
                handed = list(self._local_queue)
                self._local_queue.clear()
            for e in handed:
                e.pop("lease_ck", None)     # epoch bump zeroed budgets
                self._finish_local(e, None, None, None, "requeue")

    # -- worker->head pump ---------------------------------------------------
    def _relay_up(self, index: int, frames: list) -> bool:
        """Relay rewritten frames to the head IN ORDER, packing every
        run of >= 2 consecutive spilled ``submit`` frames into ONE
        framed multi-submit (``rpc/wire.pack_multi_submit``): a burst
        of N lease misses costs one head frame, not N.  Returns False
        when the head link is gone (the pump stops)."""
        from ..rpc import wire
        from .serialization import serialize
        i, n = 0, len(frames)
        while i < n:
            msg = frames[i]
            j = i + 1
            if self._lease is not None and msg[0] == "submit":
                while j < n and frames[j][0] == "submit":
                    j += 1
            if j - i >= 2:
                packed = wire.pack_multi_submit(
                    [serialize(f) for f in frames[i:j]])
                self._msub_batches += 1
                self._msub_frames += j - i
                msg = ("msub", packed)
            try:
                # explicit no-deadline: a large result frame draining
                # slowly is not a dead head; loss raises via on_close
                self._head.call("agent_frame", self.agent_id, index,
                                msg, timeout=None)
            except Exception:   # noqa: BLE001 — head gone: nothing to
                return False    # relay to; the on_close hook is
                #                 already ending the agent
            i = j
        return True

    def _pump(self, index: int, conn, epoch: int = 0) -> None:
        eof = False
        while not eof:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            if self._epoch != epoch:
                return      # stale worker of a replaced head: its index
                #             collides with the new pool's — go quiet
            msgs = [msg]
            if self._lease is not None:
                # greedy drain: every frame the worker already piped
                # this cycle rides one relay burst, so a fan-out's
                # consecutive spilled submits coalesce into one
                # multi-submit frame instead of one head RPC each
                try:
                    while len(msgs) < self._msub_max and conn.poll(0):
                        msgs.append(conn.recv())
                except (EOFError, OSError):
                    eof = True
            out = []
            for m in msgs:
                try:
                    m = self._rewrite_up(index, m)
                except Exception:   # noqa: BLE001 — surgery must not
                    # drop the frame; forward as-is, but a failing
                    # rewrite is a protocol bug worth surfacing
                    _LOG.warning("frame rewrite failed; forwarding raw",
                                 exc_info=True)
                if m is not None:
                    out.append(m)
            if out and not self._relay_up(index, out):
                return
        if self._epoch != epoch:
            return          # stale: do NOT EOF the new pool's worker
        self._release_index_pins(index)
        self._on_worker_gone(index)
        try:
            self._head.call("agent_eof", self.agent_id, index)
        except Exception:       # noqa: BLE001
            pass
        with self._lock:
            self._workers.pop(index, None)
            self._w_state.pop(index, None)


# ---------------------------------------------------------------------------
# head side
# ---------------------------------------------------------------------------

class _RemoteConn:
    """Virtual pipe endpoint: send = RPC to the agent; recv = queue fed
    by the hub's incoming agent_frame handler."""

    def __init__(self, spawner: "AgentSpawner", index: int):
        self._spawner = spawner
        self._index = index
        self._q: queue.Queue = queue.Queue()
        self.eof = threading.Event()

    def send(self, msg) -> None:
        self._spawner.send_to_worker(self._index, msg)

    def recv(self):
        item = self._q.get()
        if item is _EOF:
            raise EOFError("remote worker gone")
        return item

    def feed(self, msg) -> None:
        self._q.put(msg)

    def close(self) -> None:
        self.feed(_EOF)


class _RemoteProc:
    """Process facade over the agent's real worker process."""

    def __init__(self, spawner: "AgentSpawner", index: int,
                 conn: _RemoteConn, pid: int):
        self._spawner = spawner
        self._index = index
        self._conn = conn
        self.pid = pid          # the real pid on the agent machine

    def is_alive(self) -> bool:
        return not (self._conn.eof.is_set() or self._spawner._closed)

    def terminate(self) -> None:
        self._spawner.kill_worker(self._index)

    kill = terminate

    def join(self, timeout: float | None = None) -> None:
        if self._spawner._closed:
            return              # link gone: nothing to wait for
        self._conn.eof.wait(timeout)


class AgentSpawner:
    """The WorkerPool spawner seam, backed by one registered agent."""

    def __init__(self, agent_address: str, on_disconnect=None):
        from ..rpc import transport as _transport
        self._conns: dict[int, _RemoteConn] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._client = _transport.connect(agent_address,
                                 on_close=self._handle_disconnect)
        self._on_disconnect = on_disconnect

    # -- spawner interface (WorkerPool) --------------------------------------
    def spawn(self, index: int, arena_path, env_payload):
        conn = _RemoteConn(self, index)
        with self._lock:
            if self._closed:
                raise RuntimeError("agent is gone")
            self._conns[index] = conn
        try:
            pid = self._client.call("a_spawn", index, env_payload,
                                    timeout=60.0)
        except Exception:
            with self._lock:
                self._conns.pop(index, None)
            raise
        if not pid:
            with self._lock:
                self._conns.pop(index, None)
            raise RuntimeError("agent failed to spawn worker")
        return _RemoteProc(self, index, conn, pid), conn

    def stop(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._client.call("a_stop", timeout=5.0)
        except Exception:       # noqa: BLE001 — agent may already be gone
            pass
        self._client.close()
        self._drop_all()

    # -- frame plumbing ------------------------------------------------------
    def send_to_worker(self, index: int, msg) -> None:
        with self._lock:
            if self._closed or index not in self._conns:
                raise BrokenPipeError("remote worker gone")
        try:
            # no deadline: a slow worker draining a large frame is NOT a
            # dead worker (a timeout here would dead-mark it and run the
            # task twice); a truly lost link raises RpcConnectionError
            ok = self._client.call("a_send", index, msg,
                                   timeout=None)
        except Exception as e:
            raise BrokenPipeError(f"agent link lost: {e}") from e
        if not ok:
            raise BrokenPipeError("remote worker pipe closed")

    def kill_worker(self, index: int) -> None:
        try:
            self._client.call("a_kill", index, timeout=10.0)
        except Exception:       # noqa: BLE001 — best-effort, like SIGKILL
            pass                # on an already-dead pid

    def cancel_remote(self, tid_bin: bytes, force: bool) -> str | None:
        """Cancel an agent-leased task: drop it from the agent's local
        queue, or (force) kill the worker running it.  Returns the
        agent's verdict ("dequeued"/"killed"/"running"/"unknown") or
        None when the agent is unreachable — the caller decides what
        to seal from it."""
        try:
            return self._client.call("a_cancel", tid_bin, force,
                                     timeout=10.0)
        except Exception:       # noqa: BLE001
            return None

    def drain_remote(self) -> int | None:
        """Relay a node drain to the agent: it stops autonomous local
        dispatch and hands queued leases back.  Best-effort — a dead
        agent converges through the health manager's dead path."""
        try:
            return self._client.call("a_drain", timeout=10.0)
        except Exception:       # noqa: BLE001
            return None

    def set_policy(self, policy: dict) -> None:
        """Push an autonomy-policy update (job-env gating) to the
        agent; best-effort — a dropped push only disables/keeps the
        fast path until the next one."""
        try:
            self._client.call("a_policy", policy, timeout=10.0)
        except Exception:       # noqa: BLE001
            pass

    def feed_frame(self, index: int, msg) -> None:
        with self._lock:
            conn = self._conns.get(index)
        if conn is not None:
            conn.feed(msg)

    def feed_eof(self, index: int) -> None:
        with self._lock:
            conn = self._conns.pop(index, None)
        if conn is not None:
            conn.eof.set()
            conn.feed(_EOF)

    def _drop_all(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.eof.set()
            conn.feed(_EOF)

    def _handle_disconnect(self) -> None:
        """Agent link dropped: every remote worker is unreachable.
        Drain the node FIRST (remove_node → pool.shutdown latches the
        pool, so worker-reader threads exiting on the EOFs below do not
        race a respawn through this dead link), then EOF the readers."""
        with self._lock:
            already = self._closed
            self._closed = True
        if not already and self._on_disconnect is not None:
            self._on_disconnect()
        self._drop_all()


class AgentHub:
    """Head-side registry: serves agent registration + frame routing.

    Attach via ``attach(server)`` (``HeadNode`` does this; tests may
    attach to any server fronting a cluster) — it also exposes the
    head's object plane so agents can pull head-resident objects."""

    _EPOCH_KEY = b"lease_epochs"
    _EPOCH_NS = "_lease"

    def __init__(self, cluster):
        from ..common.config import get_config
        self._cluster = cluster
        self._agents: dict[str, tuple[AgentSpawner, object]] = {}
        self._agent_workers: dict[str, int] = {}
        self._lock = threading.Lock()
        # -- lease plane: the head-side single source of truth --------------
        cfg = get_config()
        self._grantor = None
        self._cfg_budget = int(cfg.lease_budget_per_class)
        self._lease_overcommit = float(cfg.lease_overcommit)
        # budget sizing knobs: an explicit lease_budget_per_class wins;
        # otherwise 'beat' reads the scheduling beat's device-priced
        # headroom off the budget board (host heuristic as fallback),
        # 'heuristic' is the pre-budget-beat workers x overcommit path
        self._budget_min = max(1, int(cfg.lease_budget_min))
        self._board = None
        if str(cfg.lease_budget_source) == "beat" and not self._cfg_budget:
            from ..leasing.board import budget_board
            self._board = budget_board()
        self._epoch_tab: dict[str, int] = {}
        if cfg.lease_plane_enabled:
            from ..leasing import LeaseGrantor, register_stats
            self._grantor = LeaseGrantor(
                budget_per_class=self._cfg_budget or self._budget_min,
                max_classes=int(cfg.lease_max_classes),
                journal=self._journal_epoch)
            self._restore_epochs()
            register_stats("head_grantor", self._grantor.stats)

    # -- epoch journal (rides the persisted GCS snapshot's KV plane) --------
    def _journal_epoch(self, node: str, epoch: int) -> None:
        """Revocation epochs persist through the cluster KV, which the
        GCS snapshot covers: a promoted standby restores the table and
        never re-issues an epoch the dead head already revoked — how
        outstanding leases survive failover."""
        import json
        self._epoch_tab[node] = int(epoch)
        try:
            self._cluster.kv.put(
                self._EPOCH_KEY, json.dumps(self._epoch_tab).encode(),
                namespace=self._EPOCH_NS)
        except Exception:   # noqa: BLE001 — journal loss degrades to
            pass            # the fence horizon, never to a crash

    def _restore_epochs(self) -> None:
        import json
        try:
            raw = self._cluster.kv.get(self._EPOCH_KEY,
                                       namespace=self._EPOCH_NS)
            if raw:
                self._epoch_tab = {str(k): int(v) for k, v
                                   in json.loads(bytes(raw)).items()}
                self._grantor.restore(self._epoch_tab)
        except Exception:   # noqa: BLE001 — corrupt journal: start
            self._epoch_tab = {}        # fresh (fencing still holds)

    def handlers(self) -> dict:
        return {
            "agent_register": self.register,
            "agent_frame": self.frame,
            "agent_eof": self.eof,
            "agent_bye": self.bye,
            "agent_sync": self.sync,
            "agent_fn": self.fn_bytes,
        }

    def fn_bytes(self, fn_id: str):
        """Serve a function's bytes from the head registry (agents
        fetch once per function for their autonomous dispatch)."""
        return self._cluster.fn_registry.get(fn_id)

    def attach(self, server) -> None:
        for name, fn in self.handlers().items():
            server.add_handler(name, fn)
        self._cluster.plane.attach(server)
        # job-env changes gate the agents' env-blind fast path
        self._cluster.on_job_env_change = self._push_policy_all

    def _push_policy_all(self, env) -> None:
        """Fire the policy push on a side thread: the caller may hold
        head-level locks (HeadNode._connect), and an unreachable agent
        must not stall it for the RPC timeout."""
        with self._lock:
            spawners = [e[0] for e in self._agents.values()]
        policy = {"fast_path": not bool(env)}

        def run() -> None:
            for sp in spawners:
                sp.set_policy(policy)
        threading.Thread(target=run, daemon=True,
                         name="agent-policy-push").start()

    def register(self, agent_id: str, agent_address: str,
                 resources: dict | None, num_workers: int,
                 labels: dict | None, plane: bool = True):
        if not plane:
            raise ValueError(
                "relay-only agents are no longer supported: every "
                "NodeAgent serves an object plane (one data-plane "
                "code path)")
        # the disconnect hook is live from the START — an agent dying
        # mid-registration must still tear down whatever exists by then
        spawner = AgentSpawner(
            agent_address,
            on_disconnect=lambda: self._on_agent_lost(agent_id))
        # route frames BEFORE adding the node: add_remote_node blocks on
        # worker-ready frames, which arrive through this table — adding
        # the entry after would drop them and wedge the registration
        with self._lock:
            self._agents[agent_id] = (spawner, None)
        try:
            node_id = self._cluster.add_remote_node(
                resources=resources, num_workers=num_workers,
                spawner=spawner, labels=labels,
                plane_address=agent_address)
        except BaseException:
            with self._lock:
                self._agents.pop(agent_id, None)
            spawner.stop()
            raise
        with self._lock:
            if agent_id not in self._agents:
                # disconnected while the node was coming up: the hook
                # already popped the entry but had no node to remove
                vanished = True
            else:
                self._agents[agent_id] = (spawner, node_id)
                vanished = False
        if vanished:
            try:
                self._cluster.remove_node(node_id)
            except (KeyError, ValueError):
                pass
            raise ConnectionError("agent disconnected during "
                                  "registration")
        with self._lock:
            self._agent_workers[agent_id] = int(num_workers)
        out = {"node_id": node_id.hex(),
               "resources": resources or dict(DEFAULT_NODE_RESOURCES),
               "fast_path": not bool(self._cluster.job_runtime_env)}
        if self._grantor is not None:
            ep, grants = self._grantor.snapshot_for(agent_id)
            out["lease"] = {"epoch": ep, "grants": grants}
        return out

    def frame(self, agent_id: str, index: int, msg) -> None:
        entry = self._agents.get(agent_id)
        if entry is None:
            return
        if isinstance(msg, tuple) and msg and msg[0] == "msub":
            # one framed multi-submit off the agent's pump: unpack the
            # individual worker submit frames (order preserved — the
            # wire round-trip is byte-exact) and feed them as if they
            # had arrived one frame each
            from ..rpc import wire
            from .serialization import deserialize
            for raw in wire.unpack_multi_submit(msg[1]):
                entry[0].feed_frame(index, deserialize(raw))
            return
        entry[0].feed_frame(index, msg)

    # -- autonomy sync (ordered refs/started/done batch from an agent) ------
    def sync(self, agent_id: str, batch: list, load: dict,
             lease_want=None):
        """Fold an agent's autonomous-dispatch batch into the head's
        authority, IN ORDER: ref-count events, started specs
        (ownership, lineage), done results (seal + complete +
        reconcile), then the node's live local load.  The per-lease
        head cost is this amortized call — the lease itself never
        touched the head.

        ``lease_want`` lists resource classes the agent spilled since
        its last sync: the grantor leases them (bounded per node) and
        the reply carries the node's current epoch + grant snapshot, so
        one spillback converts the whole rest of a repeat-class stream
        into local grants."""
        entry = self._agents.get(agent_id)
        if entry is None or entry[1] is None:
            return False
        node_id = entry[1]
        cluster = self._cluster
        row = cluster.crm.row_of(node_id)
        raylet = cluster.raylets.get(row) if row is not None else None
        if raylet is None:
            return False
        for item in batch:
            kind = item[0]
            if kind == "refs":
                cluster.ref_counter.apply_batch(
                    item[2], ("w", row, item[1]))
            elif kind == "started":
                self._sync_started(cluster, raylet, row, item[1],
                                   item[2],
                                   item[3] if len(item) > 3 else None,
                                   item[4] if len(item) > 4 else None)
            elif kind == "done":
                self._sync_done(cluster, raylet, row, item)
        raylet.agent_local_cu = dict(load) if load else None
        raylet._notify_dirty()
        if self._grantor is not None:
            fallback = self._cfg_budget or max(
                self._budget_min,
                int(self._agent_workers.get(agent_id, 2) *
                    self._lease_overcommit))
            for ck in list(lease_want or ())[:32]:
                budget = fallback
                if self._board is not None:
                    # the beat's device-priced headroom for this
                    # (class, node); floored so repeat-class pipelines
                    # stay warm even when the beat prices a node at 0
                    b = self._board.budget_for(str(ck), row)
                    if b is not None:
                        budget = max(self._budget_min, int(b))
                self._grantor.grant(agent_id, str(ck), budget)
            ep, grants = self._grantor.snapshot_for(agent_id)
            return {"ok": True, "epoch": ep, "grants": grants}
        return True

    def _sync_started(self, cluster, raylet, row: int,
                      spec_bytes: bytes, submitter: int,
                      fn_id: str | None = None,
                      fn_bytes: bytes | None = None) -> None:
        from ..common.ids import ObjectID as _OID
        from .serialization import deserialize
        tm = cluster.task_manager
        if fn_bytes is not None and fn_id is not None:
            # worker-defined fn whose submit frame never reached the
            # head: the registry must resolve fn_id for retries and
            # lineage reconstruction
            cluster.fn_registry.setdefault(fn_id, fn_bytes)
        spec = deserialize(spec_bytes)
        if tm.get(spec.task_id) is not None:
            return              # duplicate (reconnect replay)
        rec = tm.register(spec)
        rec.lineage_bytes = len(spec_bytes) + 256
        holder = ("w", row, submitter)
        for i in range(max(spec.num_returns, 0)):
            cluster.ref_counter.set_owner(
                _OID.for_task_return(spec.task_id, i + 1), holder)
        raylet.agent_inflight[spec.task_id] = rec

    def _sync_done(self, cluster, raylet, row: int, item) -> None:
        from ..common.ids import TaskID
        from .serialization import RayTaskError, WorkerCrashedError, \
            deserialize
        _kind, tid_bin, descs, contained, err_bytes, disposition = item
        tm = cluster.task_manager
        tid = TaskID(tid_bin)
        rec = raylet.agent_inflight.pop(tid, None)
        if rec is None:
            rec = tm.get(tid)
        if rec is None or rec.done:
            # a record completed elsewhere (cancel raced completion):
            # the agent-arena copies described here have no owner and
            # would leak until agent restart — free them
            from ..common.ids import ObjectID as _OID
            for d in (descs or ()):
                if d[0] == "p" and raylet.plane_address is not None:
                    cluster.plane.free_on(raylet.plane_address,
                                          [_OID(d[1])])
            return
        if disposition == "requeue":
            # never ran on the agent (stale lease, worker vanished
            # pre-exec, arg freed): re-enter global scheduling without
            # consuming a retry attempt
            raylet.submit_existing(rec)
            return
        if disposition == "retry":
            # local worker died under the task: the head owns the
            # attempt budget — resubmit through normal scheduling
            if tm.should_retry(tid):
                raylet.submit_existing(rec)
            else:
                err = RayTaskError(
                    rec.spec.function_descriptor, "worker died",
                    WorkerCrashedError(
                        "agent-local worker died executing "
                        f"{rec.spec.function_descriptor}"))
                raylet._seal_error_returns(rec, err)
                tm.complete(tid)
            return
        if err_bytes is not None:
            raylet._seal_error_returns(rec, deserialize(err_bytes))
            tm.complete(tid)
            return
        raylet._seal_contained(rec, contained)
        head_row = cluster.head().row
        for oid, d in zip(rec.return_ids, descs or ()):
            if oid in rec.dead_returns:
                if d[0] == "p" and raylet.plane_address is not None:
                    cluster.plane.free_on(raylet.plane_address, [oid])
                continue
            if d[0] == "p":
                cluster.directory.add_location(oid, row)
                cluster.store.put_remote(oid, d[2])
            else:
                cluster.seal_serialized(oid, d[1], head_row)
        tm.complete(tid)
        # close the fire-and-forget race: a return whose refs all died
        # before this registration reclaims now instead of leaking
        # (reference_counter.reconcile docstring)
        for oid in rec.return_ids:
            cluster.ref_counter.reconcile(oid)

    def eof(self, agent_id: str, index: int) -> None:
        entry = self._agents.get(agent_id)
        if entry is not None:
            entry[0].feed_eof(index)

    def bye(self, agent_id: str) -> None:
        self._on_agent_lost(agent_id)

    def shutdown(self) -> None:
        with self._lock:
            agents = list(self._agents)
        for agent_id in agents:
            self._on_agent_lost(agent_id)
        if self._grantor is not None:
            from ..leasing import unregister_stats
            unregister_stats("head_grantor")

    def _on_agent_lost(self, agent_id: str) -> None:
        with self._lock:
            entry = self._agents.pop(agent_id, None)
            self._agent_workers.pop(agent_id, None)
        if entry is None:
            return
        if self._grantor is not None:
            # node left (death, bye, shutdown): revoke its epoch so a
            # re-registration under the same id can never reuse grants
            # (journaled — survives head kill and standby promotion)
            self._grantor.drop_node(agent_id)
        spawner, node_id = entry
        # drain first so the raylet stops dispatching into the void,
        # then drop the link; remove_node tolerates an already-gone node
        if node_id is not None:
            try:
                self._cluster.remove_node(node_id)
            except (KeyError, ValueError):
                pass            # already removed / cluster torn down
        spawner.stop()
