"""Worker-node agent: joins a remote machine's workers to a head.

Reference parity: ``ray start --address=<head>`` boots a worker node
whose raylet registers with the GCS and leases local worker processes to
the cluster over gRPC (``NodeManagerService`` — SURVEY.md §1 layers 2-4,
§3.1; mount empty).  The rebuild keeps ALL scheduling/lease/env state in
the head process (single source of truth: the head's ``WorkerPool`` and
``Raylet`` run unchanged) and makes only the process transport remote:

    head                                  agent machine
    ----                                  -------------
    Raylet ── WorkerPool ── AgentSpawner ──TCP── NodeAgent ── pipe ── worker
                             (spawner seam)        (dumb relay)

- The **agent** (``NodeAgent``) is a dumb relay daemon: it spawns
  ``worker_main`` processes locally (same ``LocalSpawner`` mechanics as
  the head) and shuttles their pipe frames to/from the head over the RPC
  plane, then registers its node with the head.
- The **head** (``AgentHub`` + ``AgentSpawner``) serves the agent's
  registration, creates a normal raylet row whose pool spawns through
  the agent, and routes incoming worker frames to virtual pipe
  connections.  The raylet runs with ``inline_objects=True``: remote
  workers share no shm arena, so every object payload ships in-band
  (the reference's cross-node path similarly leaves zero-copy plasma
  behind at the node boundary).

An agent disconnect (process death, network drop) surfaces through the
RPC client's ``on_close`` and drives the existing ``remove_node`` drain:
running tasks retry elsewhere, exactly like a node death.

Limitation (v1, noted): runtime-env ``working_dir``/``py_modules``
staging paths live on the head's filesystem, so tasks with those envs
only resolve on agents sharing that filesystem.
"""

from __future__ import annotations

import queue
import threading

from ..common.ids import NodeID
from .worker_pool import LocalSpawner

_EOF = object()


# ---------------------------------------------------------------------------
# agent process side
# ---------------------------------------------------------------------------

class NodeAgent:
    """The daemon on a worker machine: spawn + relay, no state."""

    def __init__(self, head_address: str,
                 resources: dict[str, float] | None = None,
                 num_workers: int = 2,
                 labels: dict[str, str] | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        from ..rpc import RpcClient, RpcServer
        self._spawner = LocalSpawner()
        self._workers: dict[int, tuple] = {}    # index -> (proc, conn)
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self.server = RpcServer({
            "a_spawn": self._a_spawn,
            "a_send": self._a_send,
            "a_kill": self._a_kill,
            "a_stop": self._a_stop,
            "a_ping": lambda: "ok",
        }, host=host, port=port).start()
        # head link: frames flow agent->head on this client; its loss
        # (head died) ends the agent — workers without a head are orphans
        self._head = RpcClient(head_address,
                               on_close=self._stop_event.set)
        self.agent_id = NodeID.from_random().hex()
        self.node_id_hex = self._head.call(
            "agent_register", self.agent_id, self.server.address,
            resources, num_workers, labels)

    def wait_for_shutdown(self, timeout: float | None = None) -> bool:
        return self._stop_event.wait(timeout)

    def stop(self) -> None:
        try:
            self._head.call("agent_bye", self.agent_id, timeout=5.0)
        except Exception:       # noqa: BLE001 — head may already be gone
            pass
        self._a_stop()

    # -- RPC handlers (called by the head) ----------------------------------
    def _a_spawn(self, index: int, env_payload: dict | None) -> int:
        """Spawn a local worker; returns its real pid (0 = failed)."""
        proc, conn = self._spawner.spawn(index, None, env_payload)
        with self._lock:
            self._workers[index] = (proc, conn)
        threading.Thread(target=self._pump, args=(index, conn),
                         daemon=True, name=f"agent-pump-{index}").start()
        return proc.pid or 0

    def _a_send(self, index: int, msg) -> bool:
        with self._lock:
            entry = self._workers.get(index)
        if entry is None:
            return False
        try:
            entry[1].send(msg)
            return True
        except (OSError, BrokenPipeError):
            return False

    def _a_kill(self, index: int) -> None:
        with self._lock:
            entry = self._workers.get(index)
        if entry is not None:
            try:
                entry[0].terminate()
            except Exception:   # noqa: BLE001
                pass

    def _a_stop(self) -> str:
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for proc, conn in workers:
            try:
                conn.send(("shutdown",))
            except (OSError, BrokenPipeError):
                pass
        for proc, conn in workers:
            proc.join(timeout=2.0)
            if proc.is_alive():
                proc.terminate()
            try:
                conn.close()
            except Exception:   # noqa: BLE001
                pass
        self._stop_event.set()
        return "stopping"

    # -- worker->head pump ---------------------------------------------------
    def _pump(self, index: int, conn) -> None:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            try:
                self._head.call("agent_frame", self.agent_id, index, msg)
            except Exception:   # noqa: BLE001 — head gone: nothing to
                return          # relay to; the on_close hook is already
                #                 ending the agent
        try:
            self._head.call("agent_eof", self.agent_id, index)
        except Exception:       # noqa: BLE001
            pass
        with self._lock:
            self._workers.pop(index, None)


# ---------------------------------------------------------------------------
# head side
# ---------------------------------------------------------------------------

class _RemoteConn:
    """Virtual pipe endpoint: send = RPC to the agent; recv = queue fed
    by the hub's incoming agent_frame handler."""

    def __init__(self, spawner: "AgentSpawner", index: int):
        self._spawner = spawner
        self._index = index
        self._q: queue.Queue = queue.Queue()
        self.eof = threading.Event()

    def send(self, msg) -> None:
        self._spawner.send_to_worker(self._index, msg)

    def recv(self):
        item = self._q.get()
        if item is _EOF:
            raise EOFError("remote worker gone")
        return item

    def feed(self, msg) -> None:
        self._q.put(msg)

    def close(self) -> None:
        self.feed(_EOF)


class _RemoteProc:
    """Process facade over the agent's real worker process."""

    def __init__(self, spawner: "AgentSpawner", index: int,
                 conn: _RemoteConn, pid: int):
        self._spawner = spawner
        self._index = index
        self._conn = conn
        self.pid = pid          # the real pid on the agent machine

    def is_alive(self) -> bool:
        return not (self._conn.eof.is_set() or self._spawner._closed)

    def terminate(self) -> None:
        self._spawner.kill_worker(self._index)

    kill = terminate

    def join(self, timeout: float | None = None) -> None:
        if self._spawner._closed:
            return              # link gone: nothing to wait for
        self._conn.eof.wait(timeout)


class AgentSpawner:
    """The WorkerPool spawner seam, backed by one registered agent."""

    def __init__(self, agent_address: str, on_disconnect=None):
        from ..rpc import RpcClient
        self._conns: dict[int, _RemoteConn] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._client = RpcClient(agent_address,
                                 on_close=self._handle_disconnect)
        self._on_disconnect = on_disconnect

    # -- spawner interface (WorkerPool) --------------------------------------
    def spawn(self, index: int, arena_path, env_payload):
        conn = _RemoteConn(self, index)
        with self._lock:
            if self._closed:
                raise RuntimeError("agent is gone")
            self._conns[index] = conn
        try:
            pid = self._client.call("a_spawn", index, env_payload,
                                    timeout=60.0)
        except Exception:
            with self._lock:
                self._conns.pop(index, None)
            raise
        if not pid:
            with self._lock:
                self._conns.pop(index, None)
            raise RuntimeError("agent failed to spawn worker")
        return _RemoteProc(self, index, conn, pid), conn

    def stop(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._client.call("a_stop", timeout=5.0)
        except Exception:       # noqa: BLE001 — agent may already be gone
            pass
        self._client.close()
        self._drop_all()

    # -- frame plumbing ------------------------------------------------------
    def send_to_worker(self, index: int, msg) -> None:
        with self._lock:
            if self._closed or index not in self._conns:
                raise BrokenPipeError("remote worker gone")
        try:
            # no deadline: a slow worker draining a large frame is NOT a
            # dead worker (a timeout here would dead-mark it and run the
            # task twice); a truly lost link raises RpcConnectionError
            ok = self._client.call("a_send", index, msg)
        except Exception as e:
            raise BrokenPipeError(f"agent link lost: {e}") from e
        if not ok:
            raise BrokenPipeError("remote worker pipe closed")

    def kill_worker(self, index: int) -> None:
        try:
            self._client.call("a_kill", index, timeout=10.0)
        except Exception:       # noqa: BLE001 — best-effort, like SIGKILL
            pass                # on an already-dead pid

    def feed_frame(self, index: int, msg) -> None:
        with self._lock:
            conn = self._conns.get(index)
        if conn is not None:
            conn.feed(msg)

    def feed_eof(self, index: int) -> None:
        with self._lock:
            conn = self._conns.pop(index, None)
        if conn is not None:
            conn.eof.set()
            conn.feed(_EOF)

    def _drop_all(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.eof.set()
            conn.feed(_EOF)

    def _handle_disconnect(self) -> None:
        """Agent link dropped: every remote worker is unreachable.
        Drain the node FIRST (remove_node → pool.shutdown latches the
        pool, so worker-reader threads exiting on the EOFs below do not
        race a respawn through this dead link), then EOF the readers."""
        with self._lock:
            already = self._closed
            self._closed = True
        if not already and self._on_disconnect is not None:
            self._on_disconnect()
        self._drop_all()


class AgentHub:
    """Head-side registry: serves agent registration + frame routing.

    Attach its handlers to the head's RpcServer (``HeadNode`` does this;
    tests may attach to any server fronting a cluster)."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._agents: dict[str, tuple[AgentSpawner, object]] = {}
        self._lock = threading.Lock()

    def handlers(self) -> dict:
        return {
            "agent_register": self.register,
            "agent_frame": self.frame,
            "agent_eof": self.eof,
            "agent_bye": self.bye,
        }

    def register(self, agent_id: str, agent_address: str,
                 resources: dict | None, num_workers: int,
                 labels: dict | None) -> str:
        # the disconnect hook is live from the START — an agent dying
        # mid-registration must still tear down whatever exists by then
        spawner = AgentSpawner(
            agent_address,
            on_disconnect=lambda: self._on_agent_lost(agent_id))
        # route frames BEFORE adding the node: add_remote_node blocks on
        # worker-ready frames, which arrive through this table — adding
        # the entry after would drop them and wedge the registration
        with self._lock:
            self._agents[agent_id] = (spawner, None)
        try:
            node_id = self._cluster.add_remote_node(
                resources=resources, num_workers=num_workers,
                spawner=spawner, labels=labels)
        except BaseException:
            with self._lock:
                self._agents.pop(agent_id, None)
            spawner.stop()
            raise
        with self._lock:
            if agent_id not in self._agents:
                # disconnected while the node was coming up: the hook
                # already popped the entry but had no node to remove
                vanished = True
            else:
                self._agents[agent_id] = (spawner, node_id)
                vanished = False
        if vanished:
            try:
                self._cluster.remove_node(node_id)
            except (KeyError, ValueError):
                pass
            raise ConnectionError("agent disconnected during "
                                  "registration")
        return node_id.hex()

    def frame(self, agent_id: str, index: int, msg) -> None:
        entry = self._agents.get(agent_id)
        if entry is not None:
            entry[0].feed_frame(index, msg)

    def eof(self, agent_id: str, index: int) -> None:
        entry = self._agents.get(agent_id)
        if entry is not None:
            entry[0].feed_eof(index)

    def bye(self, agent_id: str) -> None:
        self._on_agent_lost(agent_id)

    def shutdown(self) -> None:
        with self._lock:
            agents = list(self._agents)
        for agent_id in agents:
            self._on_agent_lost(agent_id)

    def _on_agent_lost(self, agent_id: str) -> None:
        with self._lock:
            entry = self._agents.pop(agent_id, None)
        if entry is None:
            return
        spawner, node_id = entry
        # drain first so the raylet stops dispatching into the void,
        # then drop the link; remove_node tolerates an already-gone node
        if node_id is not None:
            try:
                self._cluster.remove_node(node_id)
            except (KeyError, ValueError):
                pass            # already removed / cluster torn down
        spawner.stop()
