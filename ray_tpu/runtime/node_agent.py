"""Worker-node agent: joins a remote machine's workers to a head.

Reference parity: ``ray start --address=<head>`` boots a worker node
whose raylet registers with the GCS and leases local worker processes to
the cluster over gRPC, with a per-node plasma store and an object
manager moving payloads between nodes directly (``NodeManagerService`` +
``src/ray/object_manager/`` — SURVEY.md §1 layers 2-4,6, §3.1, §3.3;
mount empty).  The rebuild keeps ALL scheduling/lease/env state in the
head process (the head's ``WorkerPool`` and ``Raylet`` run unchanged)
and makes the process transport AND the data plane remote:

    head                                  agent machine
    ----                                  -------------
    Raylet ── WorkerPool ── AgentSpawner ──TCP── NodeAgent ── pipe ── worker
      │ (control frames: by-REFERENCE descriptors)   │ arena+store (plane)
      └── PullManager ──(op_pull: src → dest direct)─┘

- The **agent** (``NodeAgent``) spawns ``worker_main`` processes locally
  (same ``LocalSpawner`` mechanics as the head) and shuttles their pipe
  frames to/from the head over the RPC plane.  It owns a LOCAL object
  store (arena + spill dir): its workers read plasma args zero-copy from
  the agent's arena; big task results/puts seal into it and only their
  METADATA rides to the head (``result_x``/``put_x`` frames).  Payload
  bytes move between machines over the object plane
  (``runtime/object_plane.py``) — source to destination directly, never
  through the head.
- The **head** (``AgentHub`` + ``AgentSpawner``) serves the agent's
  registration, creates a normal raylet row whose pool spawns through
  the agent, and routes incoming worker frames to virtual pipe
  connections.  The raylet runs with a ``plane_address``: exec/get
  frames carry ``("r", oid)`` descriptors that the agent resolves
  against its own arena before handing them to the worker.

An agent disconnect (process death, network drop) surfaces through the
RPC client's ``on_close`` and drives the existing ``remove_node`` drain:
running tasks retry elsewhere, objects whose only copy lived on the
agent recover via lineage or surface ``ObjectLostError`` — exactly like
a node death.

Limitation (v1, noted): runtime-env ``working_dir``/``py_modules``
staging paths live on the head's filesystem, so tasks with those envs
only resolve on agents sharing that filesystem.
"""

from __future__ import annotations

import os
import queue
import shutil
import tempfile
import threading
import uuid
from collections import deque

from ..common.ids import NodeID, ObjectID, TaskID
from .worker_pool import LocalSpawner

_EOF = object()


def _make_agent_arena(session_dir: str):
    """The agent machine's own arena (plasma analogue): /dev/shm when
    available, session dir otherwise — mirrors the head's
    ``cluster_utils._make_arena``, including reaping arenas left by
    crashed sessions (a SIGKILLed agent never runs ``_a_stop``; its
    /dev/shm file would otherwise leak RAM until reboot)."""
    from ..cluster_utils import reap_stale_arenas
    from ..common.config import get_config
    from ..native import Arena
    capacity = get_config().object_store_memory_mb * 1024 * 1024
    name = f"rt_arena_{os.getpid()}_{uuid.uuid4().hex[:8]}"
    try:
        reap_stale_arenas("/dev/shm")
        return Arena(os.path.join("/dev/shm", name), capacity, create=True)
    except OSError:
        return Arena(os.path.join(session_dir, name), capacity,
                     create=True)


# ---------------------------------------------------------------------------
# agent process side
# ---------------------------------------------------------------------------

class NodeAgent:
    """The daemon on a worker machine: spawn + relay + local object
    plane.  Frame relay stays dumb except where the data plane demands
    resolution (by-reference descriptors) or extraction (big payloads
    seal locally; metadata rides up)."""

    def __init__(self, head_address: str,
                 resources: dict[str, float] | None = None,
                 num_workers: int = 2,
                 labels: dict[str, str] | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 reconnect_timeout_s: float = 0.0):
        """``reconnect_timeout_s`` > 0 makes the agent survive a head
        restart: on link loss it retries the head address for that long
        and re-registers as a fresh node (local workers of the dead
        head's pool are reaped, the local store resets — the restarted
        head has no directory entries for it)."""
        from ..rpc import RpcClient, RpcServer
        from .object_plane import ObjectPlane
        from .object_store import MemoryStore
        self._head_address = head_address
        self._resources = resources
        self._num_workers = num_workers
        self._labels = labels
        self._reconnect_timeout = reconnect_timeout_s
        self._spawner = LocalSpawner()
        self._workers: dict[int, tuple] = {}    # index -> (proc, conn)
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._stopping = False
        self._reconnecting = False
        # registration epoch: pump threads of a PREVIOUS head's workers
        # must not relay frames/EOFs to the re-registered head (their
        # indices collide with the new pool's)
        self._epoch = 0
        # local object plane: own arena + spill dir
        self._session_dir = tempfile.mkdtemp(prefix="ray_tpu_agent_")
        self._arena = _make_agent_arena(self._session_dir)
        self.store = MemoryStore(
            arena=self._arena,
            spill_dir=os.path.join(self._session_dir, "spill"))
        self.plane = ObjectPlane(self.store)
        # descriptor pins handed to local workers: exec pins release at
        # the task's result/error frame; get-reply pin batches at the
        # worker's get_ack (FIFO — the single-threaded worker acks in
        # receive order), everything at worker EOF
        self._exec_pins: dict[tuple[int, bytes], list] = {}
        self._get_pins: dict[int, deque] = {}
        self._pin_lock = threading.Lock()
        handlers = {
            "a_spawn": self._a_spawn,
            "a_send": self._a_send,
            "a_kill": self._a_kill,
            "a_stop": self._a_stop,
            "a_ping": lambda: "ok",
        }
        handlers.update(self.plane.handlers())
        self.server = RpcServer(handlers, host=host, port=port).start()
        self.plane.serve_address = self.server.address
        # head link: frames flow agent->head on this client; its loss
        # (head died) ends the agent — or, with reconnect enabled,
        # triggers the retry/re-register loop.  The INITIAL registration
        # retries under the same budget: a head dying mid-register must
        # not strand a reconnect-enabled agent
        import time as _time
        deadline = _time.monotonic() + max(reconnect_timeout_s, 0.0)
        self._reconnecting = True   # a mid-register drop must not fork
        try:                        # a racing reconnect loop
            while True:
                try:
                    self._head = RpcClient(head_address,
                                           on_close=self._on_head_lost)
                    self.agent_id = NodeID.from_random().hex()
                    self.node_id_hex = self._head.call(
                        "agent_register", self.agent_id,
                        self.server.address, resources, num_workers,
                        labels, True)
                    break
                except Exception:
                    if _time.monotonic() >= deadline:
                        raise
                    with self._lock:    # epoch bump quiets stale pumps
                        self._epoch += 1
                        self._workers.clear()
                    _time.sleep(1.0)
        finally:
            with self._lock:
                self._reconnecting = False

    # -- head failover -------------------------------------------------------
    def _on_head_lost(self) -> None:
        if self._stopping or self._reconnect_timeout <= 0:
            self._stop_event.set()
            return
        with self._lock:
            if self._reconnecting:
                return      # one loop at a time: a client that drops
            self._reconnecting = True   # mid-register must not fork a
            #                             racing second registration
        threading.Thread(target=self._reconnect_loop, daemon=True,
                         name="agent-reconnect").start()

    def _reconnect_loop(self) -> None:
        """The head died: reap the dead pool's local workers, reset the
        local store (the restarted head has no directory rows for it),
        and re-register as a fresh node until the timeout lapses."""
        import time
        from ..rpc import RpcClient
        deadline = time.monotonic() + self._reconnect_timeout
        # new epoch FIRST: surviving pump threads of the dead head's
        # workers go quiet instead of relaying colliding indices
        with self._lock:
            self._epoch += 1
            workers = list(self._workers.values())
            self._workers.clear()
        for proc, conn in workers:
            try:
                proc.terminate()
            except Exception:   # noqa: BLE001
                pass
        with self._pin_lock:
            self._exec_pins.clear()
            self._get_pins.clear()
        self.store.delete([oid for oid, _s, _k
                           in self.store.list_objects()])
        try:
            while time.monotonic() < deadline and not self._stopping:
                head = None
                try:
                    head = RpcClient(self._head_address,
                                     on_close=self._on_head_lost)
                    # install the link BEFORE registering: the register
                    # call blocks on worker-ready frames, which the new
                    # pump threads relay through self._head/agent_id
                    self._head = head
                    self.agent_id = NodeID.from_random().hex()
                    self.node_id_hex = self._head.call(
                        "agent_register", self.agent_id,
                        self.server.address, self._resources,
                        self._num_workers, self._labels, True)
                    return      # rejoined
                except Exception:   # noqa: BLE001 — head still down
                    if head is not None:
                        head.close()
                    time.sleep(1.0)
            self._stop_event.set()
        finally:
            with self._lock:
                self._reconnecting = False

    def wait_for_shutdown(self, timeout: float | None = None) -> bool:
        return self._stop_event.wait(timeout)

    def stop(self) -> None:
        self._stopping = True
        try:
            self._head.call("agent_bye", self.agent_id, timeout=5.0)
        except Exception:       # noqa: BLE001 — head may already be gone
            pass
        self._a_stop()

    # -- RPC handlers (called by the head) ----------------------------------
    def _a_spawn(self, index: int, env_payload: dict | None) -> int:
        """Spawn a local worker attached to the AGENT's arena; returns
        its real pid (0 = failed)."""
        proc, conn = self._spawner.spawn(index, self._arena.path,
                                         env_payload)
        with self._lock:
            self._workers[index] = (proc, conn)
            epoch = self._epoch
        threading.Thread(target=self._pump, args=(index, conn, epoch),
                         daemon=True, name=f"agent-pump-{index}").start()
        return proc.pid or 0

    def _a_send(self, index: int, msg) -> bool:
        with self._lock:
            entry = self._workers.get(index)
        if entry is None:
            return False
        original = msg
        try:
            msg = self._rewrite_down(index, msg)
            if msg is None:
                return True     # swallowed: the error frame went up
        except Exception:   # noqa: BLE001 — unexpected surgery failure:
            msg = original      # forward as-is; the worker surfaces an
            #                     unresolved-descriptor error, not a hang
        try:
            entry[1].send(msg)
            return True
        except (OSError, BrokenPipeError):
            self._release_frame_pins(index, msg)
            return False

    def _a_kill(self, index: int) -> None:
        with self._lock:
            entry = self._workers.get(index)
        if entry is not None:
            try:
                entry[0].terminate()
            except Exception:   # noqa: BLE001
                pass

    def _a_stop(self) -> str:
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for proc, conn in workers:
            try:
                conn.send(("shutdown",))
            except (OSError, BrokenPipeError):
                pass
        for proc, conn in workers:
            proc.join(timeout=2.0)
            if proc.is_alive():
                proc.terminate()
            try:
                conn.close()
            except Exception:   # noqa: BLE001
                pass
        self.plane.shutdown()
        try:
            self._arena.close()
        except Exception:       # noqa: BLE001
            pass
        shutil.rmtree(self._session_dir, ignore_errors=True)
        self._stop_event.set()
        return "stopping"

    # -- data-plane frame surgery -------------------------------------------
    def _rewrite_down(self, index: int, msg):
        """Head->worker: resolve by-reference descriptors against the
        LOCAL store (pin for the read's duration).  Returns the frame to
        forward, or None to swallow it (resolution failure already sent
        an error frame up)."""
        kind = msg[0]
        if kind == "exec" and len(msg) == 6 and msg[5]:
            extern, pins = [], []
            try:
                for d in msg[5]:
                    if d[0] == "r":
                        desc = self.store.descriptor_of(ObjectID(d[1]))
                        if desc[0] == "s":
                            pins.append((ObjectID(d[1]), desc[1]))
                        extern.append(desc)
                    else:
                        extern.append(d)
            except KeyError:
                self.store.unpin(pins)
                self._send_error_up(
                    index, msg[1],
                    "task arg is not resident on this node's object "
                    "plane (transfer failed or the object was freed)")
                return None
            if pins:
                with self._pin_lock:
                    self._exec_pins[(index, msg[1])] = pins
            return msg[:5] + (extern,)
        if kind == "get_reply_x" and msg[1] == "ok":
            descs, pins = [], []
            for d in msg[2]:
                if d[0] == "r":
                    try:
                        desc = self.store.descriptor_of(ObjectID(d[1]))
                    except KeyError:
                        from .object_store import ObjectLostError
                        from .serialization import RayTaskError, serialize
                        desc = ("vb", serialize(RayTaskError(
                            "get", "object vanished from the local "
                            "plane", ObjectLostError(d[1].hex()))))
                    if desc[0] == "s":
                        pins.append((ObjectID(d[1]), desc[1]))
                    descs.append(desc)
                else:
                    descs.append(d)
            if pins:
                with self._pin_lock:
                    self._get_pins.setdefault(index,
                                              deque()).append(pins)
            return (msg[0], msg[1], descs)
        return msg

    def _rewrite_up(self, index: int, msg):
        """Worker->head: big payloads seal into the LOCAL store and only
        metadata rides up; pin releases ride the task lifecycle."""
        kind = msg[0]
        if kind in ("result", "actor_result"):
            self._release_exec_pins(index, msg[1])
            tid = TaskID(msg[1])
            descs, any_big = [], False
            for i, data in enumerate(msg[2]):
                if len(data) > self.store._threshold:
                    oid = ObjectID.for_task_return(tid, i + 1)
                    self.store.put_serialized(oid, data)
                    k, size = self.store.plasma_info(oid)
                    if k in ("shm", "spill"):
                        descs.append(("p", oid.binary(), size))
                        any_big = True
                        continue
                    # store-full in-band fallback: bytes ride up
                descs.append(("v", data))
            if any_big:
                # trailing elements (contained-ref lists) pass through
                return (kind + "_x", msg[1], descs) + tuple(msg[3:])
            return msg
        if kind in ("error", "actor_error"):
            self._release_exec_pins(index, msg[1])
            return msg
        if kind == "stream_item":
            # ("stream_item", tid, idx, payload, contained): big items
            # seal into the LOCAL arena; metadata rides up
            if len(msg[3]) > self.store._threshold:
                oid = ObjectID.for_task_return(TaskID(msg[1]), msg[2])
                self.store.put_serialized(oid, msg[3])
                k, size = self.store.plasma_info(oid)
                if k in ("shm", "spill"):
                    return ("stream_item_x", msg[1], msg[2],
                            ("p", oid.binary(), size), msg[4])
            return msg
        if kind == "put":
            if len(msg[2]) > self.store._threshold:
                oid = ObjectID(msg[1])
                self.store.put_serialized(oid, msg[2])
                k, size = self.store.plasma_info(oid)
                if k in ("shm", "spill"):
                    return ("put_x", msg[1], size) + tuple(msg[3:])
            return msg
        if kind == "get_ack":
            with self._pin_lock:
                dq = self._get_pins.get(index)
                batch = dq.popleft() if dq else None
            if batch:
                self.store.unpin(batch)
            return msg
        return msg

    def _send_error_up(self, index: int, task_id_bin: bytes,
                       message: str) -> None:
        from .serialization import RayTaskError, serialize
        try:
            self._head.call(
                "agent_frame", self.agent_id, index,
                ("error", task_id_bin,
                 serialize(RayTaskError("task", message))))
        except Exception:       # noqa: BLE001 — head gone
            pass

    def _release_exec_pins(self, index: int, task_id_bin: bytes) -> None:
        with self._pin_lock:
            pins = self._exec_pins.pop((index, task_id_bin), None)
        if pins:
            self.store.unpin(pins)

    def _release_frame_pins(self, index: int, msg) -> None:
        """A rewritten frame failed to send: release the pins it carried
        (its ack/result will never come)."""
        kind = msg[0]
        if kind == "exec":
            self._release_exec_pins(index, msg[1])
        elif kind == "get_reply_x":
            with self._pin_lock:
                dq = self._get_pins.get(index)
                batch = dq.pop() if dq else None
            if batch:
                self.store.unpin(batch)

    def _release_index_pins(self, index: int) -> None:
        """Worker died/exited: every descriptor it held is dead."""
        with self._pin_lock:
            pins = []
            for key in [k for k in self._exec_pins if k[0] == index]:
                pins.extend(self._exec_pins.pop(key))
            for batch in self._get_pins.pop(index, ()):
                pins.extend(batch)
        if pins:
            self.store.unpin(pins)

    # -- worker->head pump ---------------------------------------------------
    def _pump(self, index: int, conn, epoch: int = 0) -> None:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            if self._epoch != epoch:
                return      # stale worker of a replaced head: its index
                #             collides with the new pool's — go quiet
            try:
                msg = self._rewrite_up(index, msg)
            except Exception:   # noqa: BLE001 — surgery must not drop
                pass            # the frame; forward as-is
            try:
                self._head.call("agent_frame", self.agent_id, index, msg)
            except Exception:   # noqa: BLE001 — head gone: nothing to
                return          # relay to; the on_close hook is already
                #                 ending the agent
        if self._epoch != epoch:
            return          # stale: do NOT EOF the new pool's worker
        self._release_index_pins(index)
        try:
            self._head.call("agent_eof", self.agent_id, index)
        except Exception:       # noqa: BLE001
            pass
        with self._lock:
            self._workers.pop(index, None)


# ---------------------------------------------------------------------------
# head side
# ---------------------------------------------------------------------------

class _RemoteConn:
    """Virtual pipe endpoint: send = RPC to the agent; recv = queue fed
    by the hub's incoming agent_frame handler."""

    def __init__(self, spawner: "AgentSpawner", index: int):
        self._spawner = spawner
        self._index = index
        self._q: queue.Queue = queue.Queue()
        self.eof = threading.Event()

    def send(self, msg) -> None:
        self._spawner.send_to_worker(self._index, msg)

    def recv(self):
        item = self._q.get()
        if item is _EOF:
            raise EOFError("remote worker gone")
        return item

    def feed(self, msg) -> None:
        self._q.put(msg)

    def close(self) -> None:
        self.feed(_EOF)


class _RemoteProc:
    """Process facade over the agent's real worker process."""

    def __init__(self, spawner: "AgentSpawner", index: int,
                 conn: _RemoteConn, pid: int):
        self._spawner = spawner
        self._index = index
        self._conn = conn
        self.pid = pid          # the real pid on the agent machine

    def is_alive(self) -> bool:
        return not (self._conn.eof.is_set() or self._spawner._closed)

    def terminate(self) -> None:
        self._spawner.kill_worker(self._index)

    kill = terminate

    def join(self, timeout: float | None = None) -> None:
        if self._spawner._closed:
            return              # link gone: nothing to wait for
        self._conn.eof.wait(timeout)


class AgentSpawner:
    """The WorkerPool spawner seam, backed by one registered agent."""

    def __init__(self, agent_address: str, on_disconnect=None):
        from ..rpc import RpcClient
        self._conns: dict[int, _RemoteConn] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._client = RpcClient(agent_address,
                                 on_close=self._handle_disconnect)
        self._on_disconnect = on_disconnect

    # -- spawner interface (WorkerPool) --------------------------------------
    def spawn(self, index: int, arena_path, env_payload):
        conn = _RemoteConn(self, index)
        with self._lock:
            if self._closed:
                raise RuntimeError("agent is gone")
            self._conns[index] = conn
        try:
            pid = self._client.call("a_spawn", index, env_payload,
                                    timeout=60.0)
        except Exception:
            with self._lock:
                self._conns.pop(index, None)
            raise
        if not pid:
            with self._lock:
                self._conns.pop(index, None)
            raise RuntimeError("agent failed to spawn worker")
        return _RemoteProc(self, index, conn, pid), conn

    def stop(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._client.call("a_stop", timeout=5.0)
        except Exception:       # noqa: BLE001 — agent may already be gone
            pass
        self._client.close()
        self._drop_all()

    # -- frame plumbing ------------------------------------------------------
    def send_to_worker(self, index: int, msg) -> None:
        with self._lock:
            if self._closed or index not in self._conns:
                raise BrokenPipeError("remote worker gone")
        try:
            # no deadline: a slow worker draining a large frame is NOT a
            # dead worker (a timeout here would dead-mark it and run the
            # task twice); a truly lost link raises RpcConnectionError
            ok = self._client.call("a_send", index, msg)
        except Exception as e:
            raise BrokenPipeError(f"agent link lost: {e}") from e
        if not ok:
            raise BrokenPipeError("remote worker pipe closed")

    def kill_worker(self, index: int) -> None:
        try:
            self._client.call("a_kill", index, timeout=10.0)
        except Exception:       # noqa: BLE001 — best-effort, like SIGKILL
            pass                # on an already-dead pid

    def feed_frame(self, index: int, msg) -> None:
        with self._lock:
            conn = self._conns.get(index)
        if conn is not None:
            conn.feed(msg)

    def feed_eof(self, index: int) -> None:
        with self._lock:
            conn = self._conns.pop(index, None)
        if conn is not None:
            conn.eof.set()
            conn.feed(_EOF)

    def _drop_all(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.eof.set()
            conn.feed(_EOF)

    def _handle_disconnect(self) -> None:
        """Agent link dropped: every remote worker is unreachable.
        Drain the node FIRST (remove_node → pool.shutdown latches the
        pool, so worker-reader threads exiting on the EOFs below do not
        race a respawn through this dead link), then EOF the readers."""
        with self._lock:
            already = self._closed
            self._closed = True
        if not already and self._on_disconnect is not None:
            self._on_disconnect()
        self._drop_all()


class AgentHub:
    """Head-side registry: serves agent registration + frame routing.

    Attach via ``attach(server)`` (``HeadNode`` does this; tests may
    attach to any server fronting a cluster) — it also exposes the
    head's object plane so agents can pull head-resident objects."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._agents: dict[str, tuple[AgentSpawner, object]] = {}
        self._lock = threading.Lock()

    def handlers(self) -> dict:
        return {
            "agent_register": self.register,
            "agent_frame": self.frame,
            "agent_eof": self.eof,
            "agent_bye": self.bye,
        }

    def attach(self, server) -> None:
        for name, fn in self.handlers().items():
            server.add_handler(name, fn)
        self._cluster.plane.attach(server)

    def register(self, agent_id: str, agent_address: str,
                 resources: dict | None, num_workers: int,
                 labels: dict | None, plane: bool = False) -> str:
        # the disconnect hook is live from the START — an agent dying
        # mid-registration must still tear down whatever exists by then
        spawner = AgentSpawner(
            agent_address,
            on_disconnect=lambda: self._on_agent_lost(agent_id))
        # route frames BEFORE adding the node: add_remote_node blocks on
        # worker-ready frames, which arrive through this table — adding
        # the entry after would drop them and wedge the registration
        with self._lock:
            self._agents[agent_id] = (spawner, None)
        try:
            node_id = self._cluster.add_remote_node(
                resources=resources, num_workers=num_workers,
                spawner=spawner, labels=labels,
                plane_address=agent_address if plane else None)
        except BaseException:
            with self._lock:
                self._agents.pop(agent_id, None)
            spawner.stop()
            raise
        with self._lock:
            if agent_id not in self._agents:
                # disconnected while the node was coming up: the hook
                # already popped the entry but had no node to remove
                vanished = True
            else:
                self._agents[agent_id] = (spawner, node_id)
                vanished = False
        if vanished:
            try:
                self._cluster.remove_node(node_id)
            except (KeyError, ValueError):
                pass
            raise ConnectionError("agent disconnected during "
                                  "registration")
        return node_id.hex()

    def frame(self, agent_id: str, index: int, msg) -> None:
        entry = self._agents.get(agent_id)
        if entry is not None:
            entry[0].feed_frame(index, msg)

    def eof(self, agent_id: str, index: int) -> None:
        entry = self._agents.get(agent_id)
        if entry is not None:
            entry[0].feed_eof(index)

    def bye(self, agent_id: str) -> None:
        self._on_agent_lost(agent_id)

    def shutdown(self) -> None:
        with self._lock:
            agents = list(self._agents)
        for agent_id in agents:
            self._on_agent_lost(agent_id)

    def _on_agent_lost(self, agent_id: str) -> None:
        with self._lock:
            entry = self._agents.pop(agent_id, None)
        if entry is None:
            return
        spawner, node_id = entry
        # drain first so the raylet stops dispatching into the void,
        # then drop the link; remove_node tolerates an already-gone node
        if node_id is not None:
            try:
                self._cluster.remove_node(node_id)
            except (KeyError, ValueError):
                pass            # already removed / cluster torn down
        spawner.stop()
