"""GCS key-value store + pubsub channels.

Reference parity: the GCS hosts a namespaced KV table
(``ray.experimental.internal_kv`` — ``src/ray/gcs/gcs_server/
gcs_kv_manager.cc``: Get/Put/Del/Exists/Keys with namespace prefixes,
used for function exports, runtime-env URIs, Serve/Tune state) and a
pubsub broker (``src/ray/pubsub/``: channels with publish/subscribe,
node/actor/job change feeds) — SURVEY.md §1 layer 3; mount empty.

In-process form: one lock-guarded dict per namespace and a
callback/queue-based broker.  Subscribers either register a callback
(push) or poll a bounded per-subscriber queue (pull), matching the two
upstream consumption styles.
"""

from __future__ import annotations

import threading
from collections import deque


class KVStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[tuple[str, bytes], bytes] = {}

    def put(self, key: bytes, value: bytes, namespace: str = "",
            overwrite: bool = True) -> bool:
        """Returns whether the key EXISTED before the call (the
        reference's ``_internal_kv_put`` contract); the exists-check and
        conditional write are one atomic step under the store lock —
        put-if-absent callers (leader keys) rely on that."""
        k = (namespace, bytes(key))
        with self._lock:
            existed = k in self._data
            if overwrite or not existed:
                self._data[k] = bytes(value)
            return existed

    def get(self, key: bytes, namespace: str = "") -> bytes | None:
        with self._lock:
            return self._data.get((namespace, bytes(key)))

    def exists(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            return (namespace, bytes(key)) in self._data

    def delete(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            return self._data.pop((namespace, bytes(key)), None) is not None

    def incr(self, key: bytes, delta: int = 1,
             namespace: str = "") -> int:
        """Atomic counter add; returns the new value (missing key
        counts from 0).  The GCS-side primitive concurrent clients
        (serve load accounting) need — read-modify-write through
        get/put would lose updates."""
        k = (namespace, bytes(key))
        with self._lock:
            cur = int(self._data.get(k, b"0"))
            cur += int(delta)
            self._data[k] = str(cur).encode()
            return cur

    def keys(self, prefix: bytes = b"", namespace: str = "") -> list[bytes]:
        prefix = bytes(prefix)
        with self._lock:
            return sorted(k for (ns, k) in self._data
                          if ns == namespace and k.startswith(prefix))

    def dispatch(self, op: str, key: bytes, value: bytes | None = None,
                 namespace: str = "", overwrite: bool = True):
        """Single op->method table shared by the driver-side internal_kv
        branch and the raylet's worker frame handler — two hand-rolled
        copies would silently drift (an op added to one side would fall
        into the other's catch-all)."""
        if op == "put":
            return self.put(key, value, namespace, overwrite=overwrite)
        if op == "get":
            return self.get(key, namespace)
        if op == "del":
            return self.delete(key, namespace)
        if op == "exists":
            return self.exists(key, namespace)
        if op == "keys":
            return self.keys(key, namespace)
        if op == "incr":
            return self.incr(key, int(value), namespace)
        raise ValueError(f"unknown kv op {op!r}")

    def snapshot(self) -> dict:
        """Serializable copy (checkpoint/resume support)."""
        with self._lock:
            return dict(self._data)

    def restore(self, data: dict) -> None:
        with self._lock:
            self._data = dict(data)


class _Subscription:
    __slots__ = ("callback", "queue", "_broker", "_channel")

    def __init__(self, broker, channel, callback, maxlen):
        self._broker = broker
        self._channel = channel
        self.callback = callback
        self.queue: deque | None = None if callback else deque(maxlen=maxlen)

    def poll(self) -> list:
        """Drain queued messages (pull-style subscribers)."""
        out = []
        if self.queue is not None:
            while True:
                try:
                    out.append(self.queue.popleft())
                except IndexError:
                    return out
        return out

    def unsubscribe(self) -> None:
        self._broker._remove(self._channel, self)


class PubSub:
    """Named channels; push (callback) or pull (queue) subscribers."""

    QUEUE_MAXLEN = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, list[_Subscription]] = {}
        self.num_published = 0

    def subscribe(self, channel: str, callback=None) -> _Subscription:
        sub = _Subscription(self, channel, callback, self.QUEUE_MAXLEN)
        with self._lock:
            self._subs.setdefault(channel, []).append(sub)
        return sub

    def publish(self, channel: str, message) -> int:
        """Deliver to every subscriber; returns the receiver count.
        Callbacks run on the publisher's thread without the broker lock
        (they may re-enter publish/subscribe)."""
        with self._lock:
            subs = list(self._subs.get(channel, ()))
            self.num_published += 1
        for sub in subs:
            if sub.callback is not None:
                try:
                    sub.callback(message)
                except Exception:   # noqa: BLE001 — one bad subscriber
                    import traceback
                    traceback.print_exc()
            else:
                sub.queue.append(message)
        return len(subs)

    def _remove(self, channel: str, sub) -> None:
        with self._lock:
            lst = self._subs.get(channel)
            if lst is not None:
                try:
                    lst.remove(sub)
                except ValueError:
                    pass
                if not lst:
                    del self._subs[channel]

    def stats(self) -> dict:
        with self._lock:
            return {"num_channels": len(self._subs),
                    "num_subscribers": sum(len(v)
                                           for v in self._subs.values()),
                    "num_published": self.num_published}
