"""Prometheus metrics endpoint (observability floor).

Reference parity: upstream exports core metrics (scheduler queue depths,
object store usage, worker counts) via OpenCensus to a Prometheus
scrape endpoint on ``metrics_export_port`` (``src/ray/stats/metric_defs.cc``,
``python/ray/_private/metrics_agent.py`` — SURVEY.md §1 layer 12, §5.5;
mount empty).

Pull-model: gauges are computed at scrape time straight from the live
runtime objects (CRM arrays, raylet queues, store/pull/lineage stats) —
no sampling thread, no drift.  Text exposition format 0.0.4, the one
Prometheus scrapes.  ``metrics_export_port`` 0 disables; passing port 0
to ``MetricsExporter`` directly binds an ephemeral port (tests).
"""

from __future__ import annotations

from .http_server import BackgroundHTTPServer


def _fmt(name: str, value, help_text: str, labels: dict | None = None,
         out: list | None = None) -> None:
    out.append(f"# HELP ray_tpu_{name} {help_text}")
    out.append(f"# TYPE ray_tpu_{name} gauge")
    if labels:
        lbl = ",".join(f'{k}="{v}"' for k, v in labels.items())
        out.append(f"ray_tpu_{name}{{{lbl}}} {value}")
    else:
        out.append(f"ray_tpu_{name} {value}")


def render_metrics(cluster) -> str:
    """One scrape: the cluster's live state as Prometheus text."""
    out: list[str] = []
    raylets = list(cluster.raylets.items())

    # scheduler: queue depths + placement latency
    pending = placed = running = 0
    durations: list[float] = []
    workers_alive = workers_expected = 0
    for _row, r in raylets:
        qs = r.queue_stats()
        pending += qs["pending"]
        placed += qs["placed"]
        running += qs["running"]
        durations.extend(qs["round_durations"])
        workers_alive += r.pool.num_alive()
        workers_expected += r.pool.expected()
    _fmt("scheduler_pending_tasks", pending,
         "Tasks awaiting placement across raylets", out=out)
    _fmt("scheduler_placed_tasks", placed,
         "Tasks placed, awaiting dispatch", out=out)
    _fmt("scheduler_running_tasks", running,
         "Tasks currently executing", out=out)
    if durations:
        durations.sort()
        p50 = durations[len(durations) // 2]
        _fmt("scheduler_placement_round_p50_seconds", f"{p50:.6f}",
             "Median scheduling-round duration", out=out)
    _fmt("num_nodes", len(raylets), "Live nodes", out=out)
    _fmt("num_workers_alive", workers_alive, "Live worker processes",
         out=out)
    _fmt("num_workers_expected", workers_expected,
         "Configured worker pool size", out=out)

    # object store
    ss = cluster.store.stats()
    _fmt("object_store_objects", ss["num_objects"], "Sealed objects",
         out=out)
    _fmt("object_store_arena_bytes_in_use", ss["arena_bytes_in_use"],
         "Shared-memory arena bytes in use", out=out)
    _fmt("object_store_arena_capacity_bytes", ss["arena_capacity"],
         "Shared-memory arena capacity", out=out)
    _fmt("object_store_spilled_bytes_total", ss["spilled_bytes"],
         "Bytes spilled to disk (cumulative)", out=out)
    _fmt("object_store_restored_bytes_total", ss["restored_bytes"],
         "Bytes restored from spill (cumulative)", out=out)
    _fmt("object_store_pinned_objects", ss["num_pinned"],
         "Objects pinned by outstanding descriptors", out=out)

    # object transfer
    ps = cluster.pull_manager.stats()
    _fmt("pull_manager_pulls_total", ps["num_pulls"],
         "Completed pulls (cumulative)", out=out)
    _fmt("pull_manager_bytes_pulled_total", ps["bytes_pulled"],
         "Bytes transferred by pulls (cumulative)", out=out)
    _fmt("pull_manager_inflight_bytes", ps["inflight_bytes"],
         "Bytes in active transfers", out=out)
    obj_plane = getattr(cluster, "plane", None)
    if obj_plane is not None:
        _fmt("object_plane_blacklisted_sources",
             len(obj_plane.blacklisted_sources()),
             "Transfer sources currently blacklisted for repeated "
             "failures", out=out)

    # broadcast plane (1->N relay trees)
    broadcasts = getattr(cluster, "broadcasts", None)
    if broadcasts is not None:
        bs = broadcasts.stats()
        _fmt("broadcast_active_trees", bs["bcast_active_trees"],
             "Broadcast trees currently distributing", out=out)
        _fmt("broadcast_trees_completed_total",
             bs["bcast_trees_completed"],
             "Broadcast trees fully distributed (cumulative)", out=out)
        _fmt("broadcast_members_reached_total",
             bs["bcast_members_reached"],
             "Replicas sealed through broadcast trees (cumulative)",
             out=out)
        _fmt("broadcast_joins_total", bs["bcast_joins"],
             "Concurrent pulls grafted onto an active tree "
             "(cumulative)", out=out)
        _fmt("broadcast_relay_fanout", bs["bcast_relay_fanout"],
             "Mean children per relaying node, last tree", out=out)
        _fmt("broadcast_time_to_all_ewma_seconds",
             bs["bcast_time_to_all_ewma_s"],
             "Smoothed time-to-all-replicas across broadcasts", out=out)
        if obj_plane is not None:
            es = obj_plane.bcast.stats()
            _fmt("broadcast_chunks_relayed_total",
                 es["bcast_chunks_relayed"],
                 "Chunks served from live relay sessions (cumulative)",
                 out=out)
            _fmt("broadcast_chunks_pulled_total",
                 es["bcast_chunks_pulled"],
                 "Chunks fetched from parents by relay sessions "
                 "(cumulative)", out=out)

    # ownership / lineage
    ts = cluster.task_manager.stats()
    _fmt("lineage_retained_specs", ts["num_done_retained"],
         "Completed specs retained for reconstruction", out=out)
    _fmt("lineage_bytes", ts["lineage_bytes"],
         "Bytes of retained lineage", out=out)
    rs = cluster.ref_counter.stats()
    _fmt("refcounted_objects", rs["num_tracked"],
         "Objects with live references", out=out)
    _fmt("reconstructions_total", cluster.recovery.num_reconstructions,
         "Objects reconstructed from lineage (cumulative)", out=out)

    # health + autoscaler + events
    _fmt("health_nodes_declared_dead_total", cluster.health.num_detected,
         "Nodes declared dead by health checks (cumulative)", out=out)
    hs = cluster.health.stats()
    _fmt("health_suspect_nodes", hs["num_suspect"],
         "Nodes flagged suspect (loop-lag or breaker quarantine)",
         out=out)
    _fmt("health_quarantined_nodes", hs["num_quarantined"],
         "Nodes with an OPEN circuit breaker on their plane link",
         out=out)
    from ..rpc import breaker as _breaker
    bs = _breaker.stats()
    _fmt("rpc_breakers_open",
         sum(1 for b in bs.values() if b["state"] == "open"),
         "Peer circuit breakers currently open", out=out)
    _fmt("rpc_breaker_opens_total",
         sum(b["opens"] for b in bs.values()),
         "Circuit-breaker open transitions (cumulative)", out=out)
    from ..rpc import chaos as _chaos
    ch = _chaos.active()
    if ch is not None:
        cs = ch.status()
        for key, help_text in (
                ("num_dropped", "Messages dropped by chaos injection"),
                ("num_duplicated",
                 "Messages duplicated by chaos injection"),
                ("num_delayed", "Messages delayed by chaos injection"),
                ("num_partitioned",
                 "Messages dropped by directed partitions")):
            _fmt(f"chaos_{key}", cs[key], help_text + " (cumulative)",
                 out=out)
    if cluster.autoscaler is not None:
        a = cluster.autoscaler.stats()
        _fmt("autoscaler_nodes_launched_total", a["num_launched"],
             "Nodes launched (cumulative)", out=out)
        _fmt("autoscaler_nodes_terminated_total", a["num_terminated"],
             "Idle nodes terminated (cumulative)", out=out)
    ev = getattr(cluster, "events", None)
    if ev is not None:
        _fmt("events_emitted_total", ev.num_events,
             "Structured events emitted (cumulative)", out=out)

    # serve request plane (per-deployment, only when apps run in this
    # process — the router registry is process-local)
    try:
        from ..serve.router import request_plane_stats
        plane = request_plane_stats()
    except Exception:   # noqa: BLE001 — serve absent/unused
        plane = {}
    for dep, s in sorted(plane.items()):
        lbl = {"deployment": dep}
        _fmt("serve_replicas", s.get("replicas", 0),
             "Live replicas", lbl, out)
        _fmt("serve_queued_requests", s.get("queued", 0),
             "Requests parked in the router queue", lbl, out)
        _fmt("serve_inflight_requests", s.get("inflight", 0),
             "Requests dispatched and unfinished", lbl, out)
        _fmt("serve_qps", s.get("qps", 0),
             "Completed requests per second (5s window)", lbl, out)
        _fmt("serve_latency_p50_ms", s.get("p50_ms", 0),
             "Request latency p50 (recent window)", lbl, out)
        _fmt("serve_latency_p99_ms", s.get("p99_ms", 0),
             "Request latency p99 (recent window)", lbl, out)
        _fmt("serve_latency_ewma_ms", s.get("latency_ewma_ms", 0),
             "Request latency EWMA (autoscaler signal)", lbl, out)
        _fmt("serve_shed_requests_total", s.get("shed", 0),
             "Requests shed by admission control (cumulative)", lbl,
             out)
        _fmt("serve_expired_requests_total", s.get("expired", 0),
             "Requests dropped at deadline before dispatch "
             "(cumulative)", lbl, out)
        _fmt("serve_completed_requests_total", s.get("completed", 0),
             "Requests completed (cumulative)", lbl, out)
        if s.get("batches"):
            _fmt("serve_batches_total", s["batches"],
                 "Micro-batches executed (cumulative)", lbl, out)
            _fmt("serve_batch_size_mean", s["batch_size_mean"],
                 "Mean micro-batch size", lbl, out)
        _fmt("serve_router_shards", s.get("shards", 1),
             "Router shards for this deployment's request plane", lbl,
             out)
        _fmt("serve_gossip_digest_size", s.get("gossip_digest", 0),
             "Replica load entries on the gossip board", lbl, out)

    # model-version plane (per-deployment: current version plus any
    # in-flight rollout's phase and flip progress)
    try:
        from ..versioning import VersionRegistry
        versions = VersionRegistry().all()
    except Exception:   # noqa: BLE001 — versioning absent/unused
        versions = {}
    _PHASE_IDS = {"STAGING": 1, "BROADCASTING": 2, "FLIPPING": 3,
                  "PAUSED": 4, "SEALED": 5, "ROLLED_BACK": 6}
    for dep, rec in sorted(versions.items()):
        lbl = {"deployment": dep}
        _fmt("serve_model_version",
             int(str(rec.get("current", "v1")).lstrip("v") or 1),
             "Current model version number", lbl, out)
        ro = rec.get("rollout")
        if ro is None:
            continue
        _fmt("serve_rollout_phase", _PHASE_IDS.get(ro["phase"], 0),
             "Rollout phase (1=STAGING 2=BROADCASTING 3=FLIPPING "
             "4=PAUSED 5=SEALED 6=ROLLED_BACK)", lbl, out)
        _fmt("serve_rollout_flipped_replicas", ro.get("flipped", 0),
             "Replicas flipped to the rollout's target version", lbl,
             out)
        _fmt("serve_rollout_total_replicas", ro.get("replicas", 0),
             "Replicas the rollout set out to flip", lbl, out)

    # gossiped load board (process-local, shared by every deployment)
    try:
        from ..serve.gossip import board
        gs = board.stats()
        _fmt("serve_gossip_folds_total", gs["folds"],
             "Shard-digest folds onto the load board (cumulative)",
             out=out)
        _fmt("serve_gossip_evictions_total", gs["evicted_replicas"],
             "Replica entries evicted on membership change "
             "(cumulative)", out=out)
    except Exception:   # noqa: BLE001 — serve absent/unused
        pass

    # elastic serve<->batch capacity loaning
    loans = getattr(cluster, "loans", None)
    if loans is not None:
        ls = loans.stats()
        _fmt("serve_loans_active", ls["loans_active"],
             "Batch nodes currently loaned to the serve plane", out=out)
        _fmt("serve_loans_total", ls["loans_total"],
             "Capacity loans taken (cumulative)", out=out)
        _fmt("serve_loan_reclaims_total", ls["reclaims_total"],
             "Loans reclaimed through drain semantics (cumulative)",
             out=out)
        _fmt("serve_loans_lost_total", ls["loans_lost"],
             "Loaned nodes lost to failure, booked once (cumulative)",
             out=out)
        _fmt("serve_loan_last_reclaim_seconds",
             ls["last_reclaim_latency_s"],
             "Drain-to-restore latency of the last reclaim", out=out)
        _fmt("reverse_lends_active", ls.get("reverse_lends_active", 0),
             "Serve nodes currently lent to batch/train", out=out)
        _fmt("reverse_lends_total", ls.get("reverse_lends_total", 0),
             "Reverse lends taken (cumulative)", out=out)
        _fmt("reverse_lends_returned_total",
             ls.get("reverse_lends_returned", 0),
             "Reverse lends ended by serve pressure (cumulative)",
             out=out)
        _fmt("reverse_lends_lost_total",
             ls.get("reverse_lends_lost", 0),
             "Lent nodes lost to failure, booked once (cumulative)",
             out=out)

    # elastic training plane (driver-local ElasticTrainer runs)
    try:
        from ..train.elastic import active_train_stats
        runs = active_train_stats()
    except Exception:   # noqa: BLE001 — train plane unused
        runs = []
    for ts in runs:
        lbl = {"run": ts.get("run", "")}
        _fmt("train_epoch", ts.get("epoch") or 0,
             "Last journaled (acked) epoch of the run", labels=lbl,
             out=out)
        _fmt("train_gang_losses_total", ts.get("gang_losses", 0),
             "Gang members lost mid-collective (cumulative)",
             labels=lbl, out=out)
        _fmt("train_planned_resizes_total",
             ts.get("planned_resizes", 0),
             "Drain/loan-reclaim restarts, no failure burn "
             "(cumulative)", labels=lbl, out=out)
        _fmt("train_failures_total", ts.get("failures", 0),
             "Unexplained gang failures charged to max_failures "
             "(cumulative)", labels=lbl, out=out)
        _fmt("train_world_size", ts.get("world", 0),
             "Current gang world size", labels=lbl, out=out)
        _fmt("train_sync_broadcasts_total",
             ts.get("sync_broadcasts", 0),
             "Checkpoint fan-outs over the broadcast tree "
             "(cumulative)", labels=lbl, out=out)
        _fmt("train_ckpt_replications_total",
             ts.get("ckpt_replications", 0),
             "Checkpoint replication rounds off the writer "
             "(cumulative)", labels=lbl, out=out)
        _fmt("train_goodput_eps", ts.get("goodput_eps", 0.0),
             "Acked epochs per wall second of fit(), recovery "
             "stalls included", labels=lbl, out=out)

    # lease plane (process-local registry: agent cache, head grantor,
    # standby — whichever roles live in this process)
    try:
        from ..leasing import aggregate_stats
        lz = aggregate_stats()
    except Exception:   # noqa: BLE001 — lease plane disabled
        lz = {}
    if lz.get("sources"):
        _fmt("leases_granted_local", lz["leases_granted_local"],
             "Tasks admitted from a local lease, no head RPC "
             "(cumulative)", out=out)
        _fmt("spillbacks", lz["spillbacks"],
             "Lease misses spilled back to the head (cumulative)",
             out=out)
        _fmt("lease_revocations", lz["lease_revocations"],
             "Grants revoked by epoch advance (cumulative)", out=out)
        _fmt("lease_hit_rate", lz["lease_hit_rate"],
             "Local-grant fraction of lease decisions", out=out)
        standby = lz["sources"].get("standby") or {}
        if standby:
            _fmt("standby_promotions_total",
                 standby.get("promotions", 0),
                 "Standby-to-primary promotions (cumulative)", out=out)
            fo = standby.get("failover_ms") or []
            if fo:
                _fmt("failover_ms", fo[-1],
                     "Head-death to promoted-head-serving window of "
                     "the last failover", out=out)

    # user-defined metrics (ray_tpu.util.metrics) share the endpoint
    from ..util.metrics import render_user_metrics
    out.extend(render_user_metrics())
    return "\n".join(out) + "\n"


class MetricsExporter(BackgroundHTTPServer):
    """Scrape endpoint: ``GET /metrics`` on ``metrics_export_port``."""

    def __init__(self, cluster, port: int, host: str = "127.0.0.1"):
        self._cluster = cluster
        super().__init__(host=host, port=port, name="metrics")

    def route(self, request) -> None:
        if request.path.rstrip("/") not in ("", "/metrics"):
            self.not_found(request)
            return
        self.reply(request, render_metrics(self._cluster).encode(),
                   "text/plain; version=0.0.4")
