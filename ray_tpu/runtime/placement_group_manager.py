"""Placement-group lifecycle: gang placement + 2-phase reservation.

Reference parity: ``GcsPlacementGroupManager`` + ``GcsPlacementGroupScheduler
::ScheduleUnplacedBundles`` with 2-phase commit (PrepareBundleResources on
each raylet -> all-ack -> CommitBundleResources, any nack -> rollback) and
the committed-bundle resource shaping (``CPU_group_{pgid}`` /
``CPU_group_{i}_{pgid}`` custom resources that pg tasks request) —
``src/ray/gcs/gcs_server/gcs_placement_group_*``, SURVEY.md §3.5; mount
empty.

Placement itself is the bundle policy contract from
``ray_tpu/scheduling/bundles.py`` (device twin: ``ops.bundle_kernel``).
Groups that cannot place now go to a pending list retried on every resource
release / node arrival (event-driven via the CRM version, polled by a slow
ticker as a safety net).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..common.ids import ObjectID, PlacementGroupID, TaskID
from ..common.resources import ResourceRequest, from_cu
from ..scheduling.bundles import PlacementStrategy, schedule_bundles
from .object_ref import ObjectRef
from ..common import clock as _clk


def ready_oid_for(pg_id: PlacementGroupID) -> ObjectID:
    """Deterministic ready-marker object id for a group: resolvable from
    the pg id alone, so any process can await readiness."""
    return ObjectID.for_task_return(
        TaskID.deterministic(pg_id.binary(), _nil_actor()), 1)


def shaped_name(base: str, pg_hex: str, bundle_index: int | None = None
                ) -> str:
    if bundle_index is None:
        return f"{base}_group_{pg_hex}"
    return f"{base}_group_{bundle_index}_{pg_hex}"


def shape_request(resources: dict[str, float], pg_hex: str,
                  bundle_index: int = -1) -> dict[str, float]:
    """Rewrite a task's demand onto pg-shaped resources (reference: tasks
    under a PlacementGroupSchedulingStrategy consume ``*_group_*``).

    Indexed demand consumes BOTH the indexed and the wildcard name — the
    wildcard column is the node's total reserved capacity, so every
    admission must debit it or an indexed task and a wildcard task would
    both be admitted against one reserved bundle (reference behavior)."""
    if bundle_index < 0:
        return {shaped_name(k, pg_hex): v for k, v in resources.items()}
    out = {}
    for k, v in resources.items():
        out[shaped_name(k, pg_hex, bundle_index)] = v
        out[shaped_name(k, pg_hex)] = v
    return out


def _bundle_shaped_cu(bundle_req: ResourceRequest, pg_hex: str,
                      bundle_index: int) -> dict[str, int]:
    """The shaped cu columns one committed bundle surfaces on its node
    (indexed + wildcard) — single source for reserve AND release."""
    shaped: dict[str, int] = {}
    for kname, cu in bundle_req.cu().items():
        shaped[shaped_name(kname, pg_hex, bundle_index)] = cu
        shaped[shaped_name(kname, pg_hex)] = cu
    return shaped


@dataclass
class PlacementGroupRecord:
    pg_id: PlacementGroupID
    bundles: list[dict[str, float]]
    strategy: PlacementStrategy
    name: str | None
    state: str = "PENDING"              # PENDING | CREATED | REMOVED
    rows: list[int] = field(default_factory=list)
    ready_oid: ObjectID | None = None


class PlacementGroupManager:
    def __init__(self, cluster):
        self._cluster = cluster
        self._crm = cluster.crm
        self._store = cluster.store
        self._lock = threading.RLock()
        self._groups: dict[PlacementGroupID, PlacementGroupRecord] = {}
        self._pending: list[PlacementGroupID] = []
        self._ticker: threading.Thread | None = None
        self._stop = False

    # -- creation -----------------------------------------------------------
    def create(self, pg_id: PlacementGroupID,
               bundles: list[dict[str, float]], strategy: PlacementStrategy,
               name: str | None = None) -> ObjectID:
        ready_oid = ready_oid_for(pg_id)
        rec = PlacementGroupRecord(pg_id, [dict(b) for b in bundles],
                                   strategy, name, ready_oid=ready_oid)
        # the ready marker outlives any transient pg.ready() ObjectRef —
        # pin it against refcount reclamation until the group is removed
        self._cluster.ref_counter.pin(ready_oid)
        with self._lock:
            self._groups[pg_id] = rec
            if pg_id not in self._place_many([rec]):
                self._pending.append(pg_id)
                self._ensure_ticker()
                # a group that cannot place is autoscaler demand
                asc = getattr(self._cluster, "autoscaler", None)
                if asc is not None:
                    asc.kick()
        return ready_oid

    def pending_bundle_demand(self) -> list[ResourceRequest]:
        """Bundles of still-PENDING groups (autoscaler demand — reference:
        pending placement groups feed get_nodes_to_launch)."""
        with self._lock:
            out = []
            for pg_id in self._pending:
                rec = self._groups.get(pg_id)
                if rec is not None and rec.state == "PENDING":
                    out.extend(ResourceRequest(b) for b in rec.bundles)
            return out

    def _try_place(self, rec: PlacementGroupRecord) -> bool:
        """Place + 2-phase reserve. Caller holds the lock."""
        reqs = [ResourceRequest(b) for b in rec.bundles]
        for r in reqs:                      # intern any new resource names
            self._crm.intern_request(r)     # (lock-acquiring: grows arrays)
        width = self._crm.avail.shape[1]
        dense = np.stack([r.dense(self._crm.resource_index, width)
                          for r in reqs])
        snapshot = self._crm.snapshot()
        rows = schedule_bundles(snapshot, dense, rec.strategy, commit=False)
        if rows is None:
            return False
        return self._reserve_and_commit(rec, reqs, rows)

    def _reserve_and_commit(self, rec: PlacementGroupRecord, reqs,
                            rows) -> bool:
        """2-phase reservation of a computed placement: prepare subtracts
        base resources on each chosen raylet (re-validated against the
        LIVE view — a device batch computed on a snapshot may have raced
        a task), rolling back atomically on any failure; commit surfaces
        the shaped bundle resources and seals the ready marker."""
        prepared: list[tuple[int, ResourceRequest]] = []
        ok = True
        for b, row in enumerate(rows):
            if self._crm.subtract(int(row), reqs[b]):
                prepared.append((int(row), reqs[b]))
            else:                           # raced with a task: rollback
                ok = False
                break
        if not ok:
            for row, r in prepared:
                self._crm.add_back(row, r)
            return False
        # phase 2 — commit: surface the shaped bundle resources
        pg_hex = rec.pg_id.hex()
        for b, row in enumerate(rows):
            self._crm.add_shaped_resources(
                int(row), _bundle_shaped_cu(reqs[b], pg_hex, b))
        rec.rows = [int(r) for r in rows]
        rec.state = "CREATED"
        self._store.put(rec.ready_oid, {
            "placement_group_id": pg_hex,
            "bundles_to_node_row": rec.rows,
        })
        self._wake_raylets()
        return True

    def _place_many(self, recs: list) -> set:
        """Place a batch of pending groups; returns the placed pg ids.
        Batches at or above ``pg_device_batch_min`` run the device
        gang-placement kernel in ONE call (bit-identical to sequential
        ``schedule_bundles`` — the live path of ops/bundle_kernel.py);
        smaller batches take the per-group CPU path.  Caller holds the
        lock."""
        from ..common.config import get_config
        cfg = get_config()
        if not (cfg.scheduler_device_backend
                and len(recs) >= cfg.pg_device_batch_min):
            return {rec.pg_id for rec in recs if self._try_place(rec)}
        from ..ops.bundle_kernel import schedule_bundle_groups_np
        self.device_batches = getattr(self, "device_batches", 0) + 1
        all_reqs = []
        for rec in recs:
            reqs = [ResourceRequest(b) for b in rec.bundles]
            for r in reqs:
                self._crm.intern_request(r)
            all_reqs.append(reqs)
        width = self._crm.avail.shape[1]
        B = max(len(r) for r in all_reqs)
        P = len(recs)
        bundle_reqs = np.zeros((P, B, width), dtype=np.int32)
        valid = np.zeros((P, B), dtype=bool)
        strategies = []
        for p, reqs in enumerate(all_reqs):
            for b, r in enumerate(reqs):
                bundle_reqs[p, b] = r.dense(self._crm.resource_index,
                                            width)
                valid[p, b] = True
            strategies.append(recs[p].strategy)
        snapshot = self._crm.snapshot()
        rows, ok, _ = schedule_bundle_groups_np(
            snapshot.totals, snapshot.avail, snapshot.node_mask,
            bundle_reqs, valid, strategies)
        placed = set()
        for p, rec in enumerate(recs):
            if not ok[p]:
                continue
            group_rows = rows[p, :len(all_reqs[p])]
            if self._reserve_and_commit(rec, all_reqs[p], group_rows):
                placed.add(rec.pg_id)
        return placed

    def _wake_raylets(self) -> None:
        for raylet in list(self._cluster.raylets.values()):
            raylet._notify_dirty()

    # -- pending retry ------------------------------------------------------
    def _ensure_ticker(self) -> None:
        if self._ticker is None or not self._ticker.is_alive():
            self._ticker = threading.Thread(
                target=self._retry_loop, daemon=True, name="pg-pending")
            self._ticker.start()

    def _retry_loop(self) -> None:
        last_version = -1
        while not self._stop:
            with self._lock:
                if not self._pending:
                    return
                if self._crm.version != last_version:
                    last_version = self._crm.version
                    recs = [self._groups[pg_id]
                            for pg_id in self._pending
                            if self._groups.get(pg_id) is not None
                            and self._groups[pg_id].state == "PENDING"]
                    placed = self._place_many(recs) if recs else set()
                    self._pending = [rec.pg_id for rec in recs
                                     if rec.pg_id not in placed]
            _clk.sleep(0.05)

    # -- node death ---------------------------------------------------------
    def on_node_removed(self, row: int) -> None:
        """A node holding bundles died: release the group's surviving
        reservations and send it back to pending for rescheduling
        (reference: GcsPlacementGroupManager reschedules bundles of dead
        nodes)."""
        with self._lock:
            for rec in self._groups.values():
                if rec.state != "CREATED" or row not in rec.rows:
                    continue
                pg_hex = rec.pg_id.hex()
                for b, r in enumerate(rec.rows):
                    if r == row:
                        continue            # dead node: resources are gone
                    req = ResourceRequest(rec.bundles[b])
                    self._crm.remove_shaped_resources(
                        r, _bundle_shaped_cu(req, pg_hex, b))
                    self._crm.add_back(r, req)
                rec.rows = []
                rec.state = "PENDING"
                # retract the stale ready marker: pg.wait() must block
                # until the group is re-reserved (and the deferred-actor
                # on_ready path must not fire synchronously forever)
                self._store.delete([rec.ready_oid])
                if rec.pg_id not in self._pending:
                    self._pending.append(rec.pg_id)
            if self._pending:
                self._ensure_ticker()

    def on_node_draining(self, row: int) -> int:
        """A node holding bundles is DRAINING: release reservations on
        EVERY row — including the draining one, which is still alive so
        its base resources really do come back — and re-pend the group.
        Re-placement runs against the CRM snapshot, whose drain mask
        excludes the row, so the whole group (STRICT_PACK included)
        reschedules atomically elsewhere.  Returns how many groups were
        displaced."""
        displaced = 0
        with self._lock:
            for rec in self._groups.values():
                if rec.state != "CREATED" or row not in rec.rows:
                    continue
                pg_hex = rec.pg_id.hex()
                for b, r in enumerate(rec.rows):
                    req = ResourceRequest(rec.bundles[b])
                    self._crm.remove_shaped_resources(
                        r, _bundle_shaped_cu(req, pg_hex, b))
                    self._crm.add_back(r, req)
                rec.rows = []
                rec.state = "PENDING"
                self._store.delete([rec.ready_oid])
                if rec.pg_id not in self._pending:
                    self._pending.append(rec.pg_id)
                displaced += 1
            if self._pending:
                self._ensure_ticker()
        return displaced

    # -- removal ------------------------------------------------------------
    def remove(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            rec = self._groups.get(pg_id)
            if rec is None or rec.state == "REMOVED":
                return
            if rec.state == "PENDING":
                rec.state = "REMOVED"
                if pg_id in self._pending:
                    self._pending.remove(pg_id)
                # seal the ready marker with an error: waiters (pg.ready()
                # gets, actors parked on the marker) must WAKE and fail,
                # not hang forever (reference: actor creation fails when
                # its placement group is removed).  Woken actors re-resolve
                # through scheduling_options_for -> "dead" -> ActorDied.
                from .serialization import RayTaskError
                self._store.put(rec.ready_oid, RayTaskError(
                    "placement_group.ready",
                    f"placement group {pg_id.hex()[:12]} was removed "
                    "while pending"))
                self._wake_raylets()
                return
            pg_hex = pg_id.hex()
            for b, row in enumerate(rec.rows):
                req = ResourceRequest(rec.bundles[b])
                self._crm.remove_shaped_resources(
                    row, _bundle_shaped_cu(req, pg_hex, b))
                self._crm.add_back(row, req)
            rec.state = "REMOVED"
            self._store.delete([rec.ready_oid])
            self._cluster.ref_counter.unpin(rec.ready_oid)
        self._wake_raylets()

    # -- strategy resolution (shared by raylet + actor manager) -------------
    def scheduling_options_for(self, strategy, n_rows: int):
        """Resolve a PLACEMENT_GROUP SchedulingStrategy into scheduling
        options.  Returns (verdict, options):

        * ("ok", options)   — group reserved; affinity/mask options
        * ("park", options) — group pending; all-False mask (task parks
                              until the commit wakes the raylets)
        * ("dead", None)    — group removed/unknown/bad bundle index; the
                              caller must FAIL the task/actor
        """
        import numpy as np

        from ..scheduling.policy import SchedulingOptions, SchedulingType
        with self._lock:
            rec = self._groups.get(strategy.placement_group_id)
            if rec is None or rec.state == "REMOVED":
                return "dead", None
            if strategy.bundle_index >= len(rec.bundles):
                return "dead", None
            if rec.state != "CREATED":
                return "park", SchedulingOptions(
                    node_mask=np.zeros(n_rows, dtype=bool))
            if strategy.bundle_index >= 0:
                return "ok", SchedulingOptions(
                    scheduling_type=SchedulingType.NODE_AFFINITY,
                    node_row=rec.rows[strategy.bundle_index], soft=False)
            mask = np.zeros(n_rows, dtype=bool)
            mask[[r for r in rec.rows if r < n_rows]] = True
            return "ok", SchedulingOptions(node_mask=mask)

    # -- introspection ------------------------------------------------------
    def table(self) -> dict:
        with self._lock:
            return {
                rec.pg_id.hex(): {
                    "state": rec.state,
                    "name": rec.name,
                    "strategy": rec.strategy.name,
                    "bundles": [dict(b) for b in rec.bundles],
                    "node_rows": list(rec.rows),
                } for rec in self._groups.values()
            }

    def get(self, pg_id: PlacementGroupID) -> PlacementGroupRecord | None:
        with self._lock:
            return self._groups.get(pg_id)

    def shutdown(self) -> None:
        self._stop = True


def _nil_actor():
    from ..common.ids import ActorID, JobID
    return ActorID.nil_for_job(JobID.from_int(0))
