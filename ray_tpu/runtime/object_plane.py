"""Wire-level object plane: chunked arena-to-arena transfer between nodes.

Reference parity: upstream's ``ObjectManager`` moves sealed plasma
objects between raylets directly — receiver-driven chunked pulls through
``ObjectBufferPool``, source chosen by the ``PullManager`` cost model,
with the GCS carrying only directory updates (``src/ray/object_manager/
object_manager.cc``, ``object_buffer_pool.h`` — SURVEY.md §2.1, §3.3;
mount empty).

The rebuild's shape: every machine with an arena (the head, each node
agent) exposes data-plane RPC handlers on its existing server —

    op_stat(oid)                 -> (kind, size) of the LOCAL entry
    op_read(oid, offset, length) -> one payload chunk (pin-guarded)
    op_pull(oid, size, src_addr) -> fetch the object FROM src into the
                                    local store (receiver-driven loop)
    op_free(oids)                -> drop local copies (refcount zero)
    op_plane_stats()             -> local store stats

A transfer is always driven by the RECEIVER: the pull manager (head)
tells the destination plane to ``op_pull`` from the chosen source; the
destination then issues ``op_read`` chunk calls against the source until
the payload is complete, writing each chunk straight into its final home
(arena block or spill file — ``MemoryStore.begin_ingest``).  Payload
bytes flow source→destination only; the head sees directory updates.

Chunks ride the control RPC codec as plain ``bytes`` (no pickling of
user objects), sized by ``object_transfer_chunk_mb``.
"""

from __future__ import annotations

import threading
from collections import deque

from ..common.config import get_config
from ..common.ids import ObjectID


class PlaneTransferError(RuntimeError):
    """A chunked transfer failed (source lost the object mid-pull, link
    dropped, or the destination could not stage it)."""


class ObjectPlane:
    """One node's endpoint on the object plane: serves its local store
    and pulls remote objects into it.

    ``serve_address`` is the RPC address peers use to read from this
    plane (set when the owning server attaches the handlers); transfers
    TO this plane work without it."""

    def __init__(self, store):
        self.store = store
        self.serve_address: str | None = None
        self._peers: dict[str, object] = {}     # address -> RpcClient
        self._peers_lock = threading.Lock()
        self._gc_q: deque = deque()             # (address, [oid_bin])
        self._gc_cv = threading.Condition()
        self._gc_thread: threading.Thread | None = None
        self._stopped = False
        # stats
        self.bytes_sent = 0
        self.bytes_received = 0
        self.transfers_in = 0
        self.transfers_failed = 0

    # -- serving side (attach to an RpcServer) ------------------------------
    def handlers(self) -> dict:
        return {
            "op_stat": self._op_stat,
            "op_read": self._op_read,
            "op_pull": self._op_pull,
            "op_free": self._op_free,
            "op_plane_stats": self._op_plane_stats,
        }

    def attach(self, server) -> None:
        for name, fn in self.handlers().items():
            server.add_handler(name, fn)
        self.serve_address = server.address

    def _op_stat(self, oid_bin: bytes):
        return self.store.plasma_info(ObjectID(oid_bin))

    def _op_read(self, oid_bin: bytes, offset: int,
                 length: int) -> bytes | None:
        data = self.store.read_range(ObjectID(oid_bin), offset, length)
        if data is not None:
            self.bytes_sent += len(data)
        return data

    def _op_pull(self, oid_bin: bytes, size: int, src_addr: str) -> bool:
        """Receiver-driven fetch into the LOCAL store."""
        return self.pull_into_local(ObjectID(oid_bin), size, src_addr)

    def _op_free(self, oid_bins: list[bytes]) -> None:
        self.store.delete([ObjectID(b) for b in oid_bins])

    def _op_plane_stats(self) -> dict:
        s = self.store.stats()
        s.update({"plane_bytes_sent": self.bytes_sent,
                  "plane_bytes_received": self.bytes_received,
                  "plane_transfers_in": self.transfers_in,
                  "plane_transfers_failed": self.transfers_failed})
        return s

    # -- pulling side --------------------------------------------------------
    def pull_into_local(self, oid: ObjectID, size: int,
                        src_addr: str) -> bool:
        """Fetch ``oid`` from the plane at ``src_addr`` in chunks,
        landing bytes straight into this store (arena or spill file).
        True on success OR when local bytes already exist."""
        kind, local_size = self.store.plasma_info(oid)
        if kind in ("shm", "spill", "inband"):
            return True
        try:
            client = self._peer(src_addr)
        except OSError:
            return False
        # trust the SOURCE's size (the request's size came from the
        # metadata seal and is authoritative, but re-stat catches a
        # source that lost the object before the first chunk)
        try:
            src_kind, src_size = client.call("op_stat", oid.binary(),
                                             timeout=30.0)
        except Exception:   # noqa: BLE001 — peer gone
            self._drop_peer(src_addr)
            return False
        if src_kind not in ("shm", "spill"):
            return False
        handle = self.store.begin_ingest(oid, src_size)
        if handle is None:
            return True     # raced another ingest; bytes are local
        chunk = get_config().object_transfer_chunk_mb * (1 << 20)
        got = 0
        try:
            while got < src_size:
                n = min(chunk, src_size - got)
                data = client.call("op_read", oid.binary(), got, n,
                                   timeout=60.0)
                if not data:
                    raise PlaneTransferError(
                        f"source at {src_addr} lost "
                        f"{oid.hex()[:12]} mid-transfer")
                handle.write(got, data)
                got += len(data)
            handle.commit()
        except Exception:   # noqa: BLE001 — any failure aborts cleanly
            handle.abort()
            self.transfers_failed += 1
            return False
        self.bytes_received += src_size
        self.transfers_in += 1
        return True

    def request_remote_pull(self, dest_addr: str, oid: ObjectID,
                            size: int, src_addr: str) -> bool:
        """Tell the plane at ``dest_addr`` to pull ``oid`` from
        ``src_addr`` (payload flows source→destination directly)."""
        try:
            client = self._peer(dest_addr)
            return bool(client.call("op_pull", oid.binary(), size,
                                    src_addr, timeout=300.0))
        except Exception:   # noqa: BLE001 — dest gone: transfer failed
            self._drop_peer(dest_addr)
            return False

    def free_on(self, address: str, oids) -> None:
        """Queue a best-effort remote free (refcount hit zero); runs on
        the plane-gc thread so refcount processing never blocks on RPC."""
        with self._gc_cv:
            if self._stopped:
                return
            self._gc_q.append((address, [o.binary() for o in oids]))
            if self._gc_thread is None or not self._gc_thread.is_alive():
                self._gc_thread = threading.Thread(
                    target=self._gc_loop, daemon=True, name="plane-gc")
                self._gc_thread.start()
            self._gc_cv.notify_all()

    def _gc_loop(self) -> None:
        while True:
            with self._gc_cv:
                while not self._gc_q and not self._stopped:
                    self._gc_cv.wait()
                if self._stopped and not self._gc_q:
                    return
                address, oid_bins = self._gc_q.popleft()
            try:
                self._peer(address).call("op_free", oid_bins,
                                         timeout=10.0)
            except Exception:   # noqa: BLE001 — peer gone; its copies
                self._drop_peer(address)    # died with it

    # -- peer cache ----------------------------------------------------------
    def _peer(self, address: str):
        from ..rpc import RpcClient
        with self._peers_lock:
            client = self._peers.get(address)
            if client is not None and not client._closed:
                return client
        client = RpcClient(address)
        with self._peers_lock:
            live = self._peers.get(address)
            if live is not None and not live._closed:
                client.close()
                return live
            self._peers[address] = client
        return client

    def _drop_peer(self, address: str) -> None:
        with self._peers_lock:
            client = self._peers.pop(address, None)
        if client is not None:
            client.close()

    def shutdown(self) -> None:
        with self._gc_cv:
            self._stopped = True
            self._gc_cv.notify_all()
        with self._peers_lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for c in peers:
            c.close()
