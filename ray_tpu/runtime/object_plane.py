"""Wire-level object plane: chunked arena-to-arena transfer between nodes.

Reference parity: upstream's ``ObjectManager`` moves sealed plasma
objects between raylets directly — receiver-driven chunked pulls through
``ObjectBufferPool``, source chosen by the ``PullManager`` cost model,
with the GCS carrying only directory updates (``src/ray/object_manager/
object_manager.cc``, ``object_buffer_pool.h`` — SURVEY.md §2.1, §3.3;
mount empty).

The rebuild's shape: every machine with an arena (the head, each node
agent) exposes data-plane RPC handlers on its existing server —

    op_stat(oid)                 -> (kind, size) of the LOCAL entry
    op_fetch(oid, offset, len)   -> one RAW-channel chunk; the reply
                                    piggybacks (kind, size) so chunk 0
                                    doubles as the stat round-trip
    op_read(oid, offset, length) -> one pickled-channel chunk (fallback)
    op_pull(oid, size, src, srcs)-> fetch the object FROM src (striping
                                    over srcs) into the local store
    op_free(oids)                -> drop local copies (refcount zero)
    op_plane_stats()             -> local store + plane stats

A transfer is always driven by the RECEIVER: the pull manager (head)
tells the destination plane to ``op_pull`` from the chosen source; the
destination then issues chunk calls against the source(s) until the
payload is complete, writing each chunk straight into its final home
(arena block or spill file — ``MemoryStore.begin_ingest``).  Payload
bytes flow source→destination only; the head sees directory updates.

Throughput shape (vs the original lockstep loop):

* **Raw-frame data channel** — chunk payloads bypass the pickle codec
  in both directions (``rpc/wire.py`` raw reply frames): the source
  serves memoryview slices straight out of its shm arena / spill file,
  the receiver lands a receive-buffer view straight into the ingest
  handle.  One copy per side instead of four-plus.
* **Windowed pipelining** — up to ``object_transfer_window`` chunk
  requests ride the connection concurrently (the RpcClient demuxes by
  req_id), capped so window x chunk never exceeds the pull manager's
  in-flight quota.  Large-object throughput becomes bandwidth-bound,
  not RTT-bound.
* **Multi-source striping** — with >=2 replicas, chunk ranges stripe
  round-robin across sources; a source dying mid-transfer reassigns
  only its unfinished stripes to the survivors (and only if ALL
  sources die does the pull fail back to the PullManager's retry
  machinery).
"""

from __future__ import annotations

import queue as _queue
import threading
from collections import deque

from ..common.config import get_config
from ..common.ids import ObjectID
from ..common import clock as _clk

# payload-serving kinds (a "remote" entry has no local bytes to serve)
_SERVABLE = ("shm", "spill")
_CHUNK_TIMEOUT = 60.0


class PlaneTransferError(RuntimeError):
    """A chunked transfer failed (source lost the object mid-pull, link
    dropped, or the destination could not stage it)."""


class ObjectPlane:
    """One node's endpoint on the object plane: serves its local store
    and pulls remote objects into it.

    ``serve_address`` is the RPC address peers use to read from this
    plane (set when the owning server attaches the handlers); transfers
    TO this plane work without it."""

    def __init__(self, store):
        from ..broadcast.relay import BroadcastEndpoint
        self.store = store
        self.serve_address: str | None = None
        self._peers: dict[str, object] = {}     # address -> RpcClient
        self._peers_lock = threading.Lock()
        # broadcast plane: relay sessions + bc_* wire surface ride on
        # this plane's server and peer cache
        self.bcast = BroadcastEndpoint(self)
        # outbound pacing (plane_uplink_mbps): serialized token bucket
        # over every chunk-serving reply on this endpoint
        self._uplink_lock = threading.Lock()
        self._uplink_free = 0.0     # monotonic instant the link frees
        self._gc_q: deque = deque()             # (address, [oid_bin])
        self._gc_cv = threading.Condition()
        self._gc_thread: threading.Thread | None = None
        self._stopped = False
        # stats — serving side
        self.bytes_sent = 0
        self.bytes_sent_raw = 0
        self.bytes_sent_pickled = 0
        # stats — pulling side
        self.bytes_received = 0
        self.bytes_received_raw = 0
        self.bytes_received_pickled = 0
        self.transfers_in = 0
        self.transfers_failed = 0
        self.stripe_retries = 0         # chunk ranges reassigned after
        #                                 a source died mid-stripe
        self.window_occupancy = 0       # chunk requests in flight NOW
        self.window_peak = 0            # high-water mark of the above
        self.last_transfer_mbps = 0.0   # most recent completed transfer
        self.ewma_transfer_mbps = 0.0   # smoothed across transfers
        # source blacklist: addr -> [consecutive failures, last failure
        # monotonic].  A source that times out / short-chunks repeatedly
        # is skipped for plane_source_blacklist_s while ANY other
        # replica remains — striped pulls stop re-trying a gray link on
        # every transfer (failures also feed its peer circuit breaker)
        self._blk_lock = threading.Lock()
        self._src_fail: dict[str, list] = {}

    # -- serving side (attach to an RpcServer) ------------------------------
    def handlers(self) -> dict:
        out = {
            "op_stat": self._op_stat,
            "op_read": self._op_read,
            "op_fetch": self._op_fetch,
            "op_pull": self._op_pull,
            "op_free": self._op_free,
            "op_plane_stats": self._op_plane_stats,
        }
        out.update(self.bcast.handlers())
        return out

    def attach(self, server) -> None:
        for name, fn in self.handlers().items():
            server.add_handler(name, fn)
        self.serve_address = server.address

    def _op_stat(self, oid_bin: bytes):
        return self.store.plasma_info(ObjectID(oid_bin))

    def throttle_uplink(self, nbytes: int) -> None:
        """Outbound pacing: when ``plane_uplink_mbps`` caps this
        endpoint's serving rate, delay the reply until the modeled link
        frees.  Token-bucket over a shared next-free instant; the sleep
        runs OUTSIDE the lock, so concurrent chunk serves queue behind
        each other exactly like frames on one uplink."""
        rate = get_config().plane_uplink_mbps
        if rate <= 0 or nbytes <= 0:
            return
        cost = nbytes / (rate * (1 << 20))
        with self._uplink_lock:
            now = _clk.monotonic()
            start = max(now, self._uplink_free)
            self._uplink_free = start + cost
            wait = start + cost - now
        if wait > 0:
            _clk.sleep(wait)

    def _op_read(self, oid_bin: bytes, offset: int,
                 length: int) -> bytes | None:
        """Pickled-channel chunk (compat / raw-channel-off fallback)."""
        data = self.store.read_range(ObjectID(oid_bin), offset, length)
        if data is not None:
            self.bytes_sent += len(data)
            self.bytes_sent_pickled += len(data)
            self.throttle_uplink(len(data))
        return data

    def _op_fetch(self, oid_bin: bytes, offset: int, length: int):
        """Raw-channel chunk.  The reply's meta carries this store's
        (kind, size) for the object, so the FIRST chunk request doubles
        as the stat round-trip — small objects complete in one RTT.
        An empty payload with a non-servable kind means 'no local
        bytes' (the puller fails over to another source)."""
        from ..rpc.wire import RawResult
        oid = ObjectID(oid_bin)
        kind, size = self.store.plasma_info(oid)
        if kind not in _SERVABLE:
            return RawResult((kind, size))
        buf, release = self.store.read_range_view(oid, offset, length)
        if buf is None:
            # entry vanished between stat and read (freed mid-transfer)
            return RawResult(self.store.plasma_info(oid))
        n = buf.nbytes if isinstance(buf, memoryview) else len(buf)
        self.bytes_sent += n
        self.bytes_sent_raw += n
        self.throttle_uplink(n)
        return RawResult((kind, size), buf, release=release)

    def _op_pull(self, oid_bin: bytes, size: int, src_addr: str,
                 src_addrs: tuple = ()) -> bool:
        """Receiver-driven fetch into the LOCAL store."""
        return self.pull_into_local(ObjectID(oid_bin), size, src_addr,
                                    src_addrs)

    def _op_free(self, oid_bins: list[bytes]) -> None:
        self.store.delete([ObjectID(b) for b in oid_bins])

    def stats(self) -> dict:
        """Plane-only counters (no store stats): the observability
        surface ``PullManager.stats`` and ``ray_tpu status`` merge."""
        return {
            "plane_bytes_sent": self.bytes_sent,
            "plane_bytes_received": self.bytes_received,
            "plane_raw_bytes_sent": self.bytes_sent_raw,
            "plane_pickled_bytes_sent": self.bytes_sent_pickled,
            "plane_raw_bytes_received": self.bytes_received_raw,
            "plane_pickled_bytes_received": self.bytes_received_pickled,
            "plane_transfers_in": self.transfers_in,
            "plane_transfers_failed": self.transfers_failed,
            "plane_stripe_retries": self.stripe_retries,
            "plane_window_occupancy": self.window_occupancy,
            "plane_window_peak": self.window_peak,
            "plane_last_transfer_mbps": round(self.last_transfer_mbps, 2),
            "plane_ewma_transfer_mbps": round(self.ewma_transfer_mbps, 2),
            "plane_blacklisted_sources": len(self.blacklisted_sources()),
            **self.bcast.stats(),
        }

    def _op_plane_stats(self) -> dict:
        s = self.store.stats()
        s.update(self.stats())
        return s

    # -- source blacklist (gray-failure quarantine for striped pulls) --------
    def _note_source_failure(self, addr: str) -> None:
        from ..rpc import breaker as _breaker
        _breaker.record_failure(addr)
        now = _clk.monotonic()
        ttl = get_config().plane_source_blacklist_s
        with self._blk_lock:
            row = self._src_fail.get(addr)
            if row is None or now - row[1] > ttl:
                self._src_fail[addr] = [1, now]
            else:
                row[0] += 1
                row[1] = now

    def _note_source_ok(self, addr: str) -> None:
        with self._blk_lock:
            self._src_fail.pop(addr, None)

    def _blacklisted(self, addr: str) -> bool:
        cfg = get_config()
        with self._blk_lock:
            row = self._src_fail.get(addr)
            if row is None:
                return False
            if _clk.monotonic() - row[1] > cfg.plane_source_blacklist_s:
                del self._src_fail[addr]    # decayed: forgiven
                return False
            return row[0] >= cfg.plane_source_blacklist_failures

    def blacklisted_sources(self) -> list[str]:
        return [a for a in list(self._src_fail) if self._blacklisted(a)]

    # -- pulling side --------------------------------------------------------
    def pull_into_local(self, oid: ObjectID, size: int, src_addr: str,
                        src_addrs: tuple = ()) -> bool:
        """Fetch ``oid`` from the plane at ``src_addr`` (striping across
        ``src_addrs`` replicas when profitable), landing bytes straight
        into this store (arena or spill file).  True on success OR when
        local bytes already exist."""
        kind, _local_size = self.store.plasma_info(oid)
        if kind in ("shm", "spill", "inband"):
            return True
        cfg = get_config()
        raw = cfg.object_transfer_raw_channel
        chunk = cfg.object_transfer_chunk_mb * (1 << 20)
        # candidate sources: primary first, deduped, never ourselves
        sources = []
        for a in (src_addr, *src_addrs):
            if a and a != self.serve_address and a not in sources:
                sources.append(a)
        # skip blacklisted sources while any clean replica remains (a
        # fully-blacklisted set still pulls: degraded beats impossible)
        clean = [a for a in sources if not self._blacklisted(a)]
        if clean:
            sources = clean
        # -- first round-trip: chunk 0 doubles as the stat ------------------
        # (trust the SOURCE's size: the request's size came from the
        # metadata seal and is authoritative, but the piggybacked stat
        # catches a source that lost the object before the first chunk)
        primary = first_data = None
        src_size = 0
        for addr in list(sources):
            try:
                client = self._peer(addr)
                if raw:
                    rep = client.call("op_fetch", oid.binary(), 0, chunk,
                                      timeout=_CHUNK_TIMEOUT)
                    src_kind, src_size = rep.meta
                    first_data = rep.payload
                else:
                    src_kind, src_size = client.call(
                        "op_stat", oid.binary(), timeout=30.0)
            except Exception:   # noqa: BLE001 — peer gone: try the next
                self._drop_peer(addr)
                self._note_source_failure(addr)
                sources.remove(addr)
                continue
            if src_kind in _SERVABLE and src_size > 0:
                primary = addr
                break
            sources.remove(addr)    # alive but no longer has the bytes
        if primary is None:
            self.transfers_failed += 1
            return False
        handle = self.store.begin_ingest(oid, src_size)
        if handle is None:
            return True     # raced another ingest; bytes are local
        if raw and src_size > chunk:
            # warm the landing pages while chunks are in flight: tmpfs
            # first-touch faults otherwise serialize into every chunk
            # landing (~3x the cost on a cold arena block)
            threading.Thread(target=handle.prefault,
                             name="plane-prefault", daemon=True).start()
        t0 = _clk.monotonic()
        try:
            got = 0
            if raw and first_data is not None and len(first_data) > 0:
                handle.write(0, first_data)
                got = len(first_data)
            if got < src_size:
                self._pipelined_fetch(oid, handle, got, src_size,
                                      sources, chunk, raw)
            handle.commit()
        except Exception:   # noqa: BLE001 — any failure aborts cleanly
            handle.abort()
            self.transfers_failed += 1
            return False
        dt = max(_clk.monotonic() - t0, 1e-9)
        mbps = src_size / (1 << 20) / dt
        self.last_transfer_mbps = mbps
        self.ewma_transfer_mbps = (mbps if self.ewma_transfer_mbps == 0
                                   else 0.8 * self.ewma_transfer_mbps
                                   + 0.2 * mbps)
        self.bytes_received += src_size
        if raw:
            self.bytes_received_raw += src_size
        else:
            self.bytes_received_pickled += src_size
        self.transfers_in += 1
        self._note_source_ok(primary)
        return True

    def _pipelined_fetch(self, oid: ObjectID, handle, start: int,
                         src_size: int, sources: list[str], chunk: int,
                         raw: bool) -> None:
        """Windowed, striped chunk fetch: keep up to W chunk requests in
        flight across the source set, writing completions straight into
        the ingest handle.  A failing source gets its unfinished stripes
        reassigned to the survivors; only when ALL sources are gone does
        the transfer raise (the PullManager's retry machinery takes over
        from there)."""
        cfg = get_config()
        stripe_min = cfg.object_transfer_stripe_min_mb * (1 << 20)
        if src_size < stripe_min or len(sources) < 2:
            srcs = sources[:1]
        else:
            srcs = list(sources)
        # the configured window is PER SOURCE (striping across N
        # replicas keeps each connection's pipeline at full depth), but
        # the existing pull quota still bounds receive-side memory:
        # never hold more in-flight chunk bytes than it allows
        window = max(1, int(cfg.object_transfer_window)) * len(srcs)
        quota = cfg.pull_manager_max_inflight_mb * (1 << 20)
        window = max(1, min(window, max(1, quota // chunk)))
        method = "op_fetch" if raw else "op_read"
        oid_bin = oid.binary()
        # direct landing: raw chunk payloads are received straight into
        # the ingest block (shm only; view() is None for spill/in-band
        # ingests and the buffered path takes over).  sink_live gates
        # every grant: once the transfer unwinds, no late reply may
        # write into a block that abort() is about to free.
        can_sink = raw and getattr(handle, "view", None) is not None
        sink_live = [True]

        def make_sink(off: int, ln: int):
            if not can_sink:
                return None

            def sink(payload_len: int):
                # a short reply (source lost the bytes) must NOT land:
                # drain-side length checks still gate success
                if not sink_live[0] or payload_len != ln:
                    return None
                return handle.view(off, ln)
            return sink

        # chunk ranges still to fetch, striped round-robin per source
        assign: dict[str, deque] = {a: deque() for a in srcs}
        ranges = [(off, min(chunk, src_size - off))
                  for off in range(start, src_size, chunk)]
        for j, rng in enumerate(ranges):
            assign[srcs[j % len(srcs)]].append(rng)

        done_q: _queue.Queue = _queue.Queue()
        inflight: dict[tuple, object] = {}      # (addr, off, ln) -> fut
        dead: set[str] = set()
        written = start

        def fail_source(addr: str) -> None:
            """Reassign a dead source's unfinished stripes to survivors
            (its in-flight chunks error back through done_q and are
            reassigned there, one by one)."""
            if addr in dead:
                return
            dead.add(addr)
            self._drop_peer(addr)
            self._note_source_failure(addr)
            survivors = [a for a in srcs if a not in dead]
            if not survivors:
                return      # the pump/drain loop raises
            moved = assign.pop(addr, deque())
            self.stripe_retries += len(moved)
            for j, rng in enumerate(moved):
                assign[survivors[j % len(survivors)]].append(rng)

        def pump() -> None:
            """Top up the window from the per-source stripe queues."""
            while len(inflight) < window:
                addr = next((a for a in srcs
                             if a not in dead and assign.get(a)), None)
                if addr is None:
                    return
                off, ln = assign[addr].popleft()
                token = (addr, off, ln)
                try:
                    fut = self._peer(addr).call_async(
                        method, oid_bin, off, ln,
                        on_done=lambda t=token: done_q.put(t),
                        sink=make_sink(off, ln))
                except Exception:   # noqa: BLE001 — send/connect failed
                    assign[addr].appendleft((off, ln))
                    fail_source(addr)
                    if not any(a not in dead for a in srcs):
                        raise PlaneTransferError(
                            f"all sources lost {oid.hex()[:12]} "
                            "mid-transfer") from None
                    continue
                inflight[token] = fut
                self.window_occupancy += 1
                self.window_peak = max(self.window_peak,
                                       len(inflight))

        try:
            pump()
            while inflight:
                try:
                    token = done_q.get(timeout=_CHUNK_TIMEOUT)
                except _queue.Empty:
                    raise PlaneTransferError(
                        f"transfer of {oid.hex()[:12]} stalled: no "
                        f"chunk completion in {_CHUNK_TIMEOUT}s") \
                        from None
                fut = inflight.pop(token, None)
                if fut is None:
                    continue
                self.window_occupancy -= 1
                addr, off, ln = token
                data = landed = None
                try:
                    rep = fut.result(0)
                    if raw:
                        data = rep.payload
                        # payload None = the reader thread received the
                        # bytes straight into our ingest view (the sink
                        # only accepts an exact-length payload)
                        landed = data is None
                    else:
                        data = rep
                except Exception:   # noqa: BLE001 — chunk RPC died
                    data = None
                if landed:
                    written += ln
                elif data is not None and len(data) == ln:
                    handle.write(off, data)
                    written += ln
                else:
                    # short/empty/error chunk: the source lost the
                    # object or the link — move this stripe (and the
                    # rest of its queue) to the survivors
                    fail_source(addr)
                    survivors = [a for a in srcs if a not in dead]
                    if not survivors:
                        raise PlaneTransferError(
                            f"all sources lost {oid.hex()[:12]} "
                            "mid-transfer")
                    self.stripe_retries += 1
                    assign[min(survivors,
                               key=lambda a: len(assign[a]))] \
                        .append((off, ln))
                pump()
        finally:
            # a failed transfer's block is about to be freed: stop
            # granting sinks, sever connections still owing chunk bytes
            # (a late reply must never recv_into the freed block), and
            # confirm in-flight receives resolved before unwinding
            sink_live[0] = False
            if inflight:
                for (addr, _o, _l), fut in inflight.items():
                    if not fut.done():
                        self._drop_peer(addr)
                deadline = _clk.monotonic() + 5.0
                for fut in inflight.values():
                    if not fut.wait(max(0.0,
                                        deadline - _clk.monotonic())):
                        break
                # occupancy must not leak
                self.window_occupancy -= len(inflight)
        if written != src_size:
            raise PlaneTransferError(
                f"transfer of {oid.hex()[:12]} incomplete: "
                f"{written}/{src_size} bytes")

    # -- broadcast (1->N) ----------------------------------------------------
    def broadcast(self, oid: ObjectID, member_addrs, size: int = 0,
                  fanout: int | None = None,
                  timeout: float | None = None) -> dict:
        """Distribute a locally sealed object to every plane in
        ``member_addrs`` through a relay tree rooted HERE (this plane
        must hold the bytes).  Plane-level primitive: with no bandwidth
        matrix in sight the tree is index-ordered balanced F-ary
        (``broadcast/plan.py``); the cluster-level coordinator
        (``BroadcastManager``) shapes topology-aware trees instead.
        Returns {"ok", "reached": [addr...], "failed": [addr...]}."""
        from ..broadcast.plan import balanced_plan
        kind, local_size = self.store.plasma_info(oid)
        if kind not in _SERVABLE:
            return {"ok": False, "reached": [], "failed":
                    list(member_addrs), "error": "no local bytes"}
        size = int(size) or int(local_size)
        cfg = get_config()
        chunk = cfg.broadcast_chunk_mb * (1 << 20)
        members = [a for a in dict.fromkeys(member_addrs)
                   if a and a != self.serve_address]
        plan = balanced_plan(members, self.serve_address, fanout)
        bcast_id = f"{oid.hex()[:16]}.p{id(plan) & 0xffffff:x}"
        futs = []
        failed = []
        for addr in plan.order:
            sources = [a for a in plan.fallbacks(addr) if a != addr]
            try:
                fut = self._peer(addr).call_async(
                    "bc_begin", bcast_id, oid.binary(), size,
                    tuple(sources), chunk)
            except Exception:   # noqa: BLE001 — member unreachable
                self._drop_peer(addr)
                failed.append(addr)
                continue
            futs.append((addr, fut))
        per_member = timeout if timeout is not None else \
            cfg.broadcast_fetch_timeout_s + max(60.0, size / (1 << 20))
        reached = []
        for addr, fut in futs:
            try:
                res = fut.result(per_member)
                ok = bool(res.get("ok"))
            except Exception:   # noqa: BLE001 — member died mid-session
                self._drop_peer(addr)
                ok = False
            (reached if ok else failed).append(addr)
        return {"ok": not failed, "reached": reached, "failed": failed}

    def request_remote_pull(self, dest_addr: str, oid: ObjectID,
                            size: int, src_addr: str,
                            src_addrs: tuple = ()) -> bool:
        """Tell the plane at ``dest_addr`` to pull ``oid`` from
        ``src_addr`` (payload flows source→destination directly)."""
        try:
            client = self._peer(dest_addr)
            return bool(client.call("op_pull", oid.binary(), size,
                                    src_addr, tuple(src_addrs),
                                    timeout=300.0))
        except Exception:   # noqa: BLE001 — dest gone: transfer failed
            self._drop_peer(dest_addr)
            return False

    def free_on(self, address: str, oids) -> None:
        """Queue a best-effort remote free (refcount hit zero); runs on
        the plane-gc thread so refcount processing never blocks on RPC."""
        with self._gc_cv:
            if self._stopped:
                return
            self._gc_q.append((address, [o.binary() for o in oids]))
            if self._gc_thread is None or not self._gc_thread.is_alive():
                self._gc_thread = threading.Thread(
                    target=self._gc_loop, daemon=True, name="plane-gc")
                self._gc_thread.start()
            self._gc_cv.notify_all()

    def _gc_loop(self) -> None:
        while True:
            with self._gc_cv:
                while not self._gc_q and not self._stopped:
                    self._gc_cv.wait()
                if self._stopped and not self._gc_q:
                    return
                address, oid_bins = self._gc_q.popleft()
            try:
                self._peer(address).call("op_free", oid_bins,
                                         timeout=10.0)
            except Exception:   # noqa: BLE001 — peer gone; its copies
                self._drop_peer(address)    # died with it

    # -- peer cache ----------------------------------------------------------
    def _peer(self, address: str):
        from ..rpc import transport as _transport
        with self._peers_lock:
            client = self._peers.get(address)
            if client is not None and not client._closed:
                return client
        # plane reads are idempotent: retry on timeout/conn-loss, and
        # enforce the peer's circuit breaker so a quarantined link fails
        # fast into the blacklist instead of eating a chunk timeout
        client = _transport.connect(address,
                                    retryable=frozenset({"op_stat", "op_free",
                                                         "op_plane_stats"}),
                                    breaker=True)
        with self._peers_lock:
            live = self._peers.get(address)
            if live is not None and not live._closed:
                client.close()
                return live
            self._peers[address] = client
        return client

    def _drop_peer(self, address: str) -> None:
        with self._peers_lock:
            client = self._peers.pop(address, None)
        if client is not None:
            client.close()

    def shutdown(self) -> None:
        with self._gc_cv:
            self._stopped = True
            self._gc_cv.notify_all()
        with self._peers_lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for c in peers:
            c.close()
