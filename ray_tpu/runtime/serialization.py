"""Serialization + task error types.

Reference parity: upstream serializes with pickle5 + cloudpickle and wraps
user exceptions in ``RayTaskError`` so a failed task's error propagates
through ``ray.get`` at the caller (``python/ray/_private/serialization.py``,
``python/ray/exceptions.py`` — SURVEY.md §2.2; mount empty).

cloudpickle handles closures, lambdas and ``__main__``-defined functions,
which plain pickle cannot ship to spawned workers.
"""

from __future__ import annotations

import traceback

import cloudpickle


def serialize(value) -> bytes:
    return cloudpickle.dumps(value)


def deserialize(data: bytes):
    return cloudpickle.loads(data)


class RayError(Exception):
    """Base for framework-raised errors."""


class RayTaskError(RayError):
    """A task raised; re-raised at every ray.get of its outputs.

    Stored AS the task's result objects, so any number of gets — local or
    remote, now or later — observe the failure (reference behavior).
    """

    def __init__(self, function_name: str, tb: str,
                 cause: BaseException | None = None):
        self.function_name = function_name
        self.tb = tb
        self.cause = cause
        super().__init__(
            f"task {function_name} failed:\n{tb}")

    def __reduce__(self):
        # Exception's default reduce replays self.args (the formatted
        # message) into __init__, which has a different signature
        return (RayTaskError, (self.function_name, self.tb, self.cause))

    @classmethod
    def from_exception(cls, function_name: str,
                       exc: BaseException) -> "RayTaskError":
        tb = "".join(traceback.format_exception(type(exc), exc,
                                                exc.__traceback__))
        # keep the cause only if it survives pickling (user exceptions may
        # hold unpicklable state; the traceback string always survives)
        try:
            cloudpickle.dumps(exc)
            cause = exc
        except Exception:
            cause = None
        return cls(function_name, tb, cause)


class WorkerCrashedError(RayError):
    """The worker process executing the task died (reference:
    ``ray.exceptions.WorkerCrashedError``)."""


class TaskCancelledError(RayError):
    """The task was cancelled before/while running (reference:
    ``ray.exceptions.TaskCancelledError``)."""


class ActorDiedError(RayError):
    """The actor died before/while executing the method call (reference:
    ``ray.exceptions.RayActorError``)."""
