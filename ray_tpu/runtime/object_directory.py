"""Object directory: which simulated nodes hold a copy of each object.

Reference parity: upstream's ``ObjectDirectory`` (``src/ray/object_manager/
object_directory.cc``) tracks object locations (via GCS/owner subscription)
so the ``PullManager`` can pick transfer sources; per-node plasma stores
make locality real (SURVEY.md §1 layer 6, §3.3; mount empty).

Here the arena is physically one shared mapping (the simulated-cluster
form, like upstream's ``cluster_utils.Cluster`` on one machine), so
locality is a *directory* property: large (plasma-routed) objects are
born on the node that produced them and gain locations as pulls complete.
Small in-band values live in the owner's memory store and ship with task
specs — they have no directory entry, matching upstream (only plasma
objects transfer through the object manager).
"""

from __future__ import annotations

import threading
from typing import Iterable

from ..common.ids import ObjectID


class ObjectDirectory:
    def __init__(self):
        self._lock = threading.Lock()
        self._locs: dict[ObjectID, set[int]] = {}

    def add_location(self, object_id: ObjectID, row: int) -> None:
        with self._lock:
            self._locs.setdefault(object_id, set()).add(row)

    def locations(self, object_id: ObjectID) -> tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._locs.get(object_id, ())))

    def has_location(self, object_id: ObjectID, row: int) -> bool:
        with self._lock:
            return row in self._locs.get(object_id, ())

    def is_tracked(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._locs

    def drop(self, object_ids: Iterable[ObjectID]) -> None:
        """Object deleted cluster-wide (refcount zero / loss)."""
        with self._lock:
            for oid in object_ids:
                self._locs.pop(oid, None)

    def sole_copies_on(self, row: int) -> list[ObjectID]:
        """Objects whose ONLY copy lives on ``row`` (a node holding any is
        not safe to terminate; the autoscaler migrates them first)."""
        out = []
        with self._lock:
            for oid, rows in self._locs.items():
                if rows == {row}:
                    out.append(oid)
        return out

    def on_node_removed(self, row: int) -> list[ObjectID]:
        """Node death: its copies vanish.  Returns objects whose LAST copy
        was on the dead node — they are lost (upstream: reconstructed via
        lineage or surfaced as ObjectLostError, SURVEY §5.3)."""
        lost = []
        with self._lock:
            for oid, rows in list(self._locs.items()):
                rows.discard(row)
                if not rows:
                    del self._locs[oid]
                    lost.append(oid)
        return lost

    def location_matrix(self, object_ids: list[ObjectID], n_rows: int):
        """(len(ids), n_rows) bool location mask for the pull kernel."""
        import numpy as np
        out = np.zeros((len(object_ids), n_rows), dtype=bool)
        with self._lock:
            for i, oid in enumerate(object_ids):
                for r in self._locs.get(oid, ()):
                    if r < n_rows:
                        out[i, r] = True
        return out

    def stats(self) -> dict:
        with self._lock:
            copies = sum(len(v) for v in self._locs.values())
            return {"num_tracked": len(self._locs), "num_copies": copies}
