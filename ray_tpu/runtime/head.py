"""Head daemon: a long-lived process hosting the cluster + control RPC.

Reference parity: the head node runs ``gcs_server`` + raylet + dashboard,
and remote drivers attach via ``ray.init("ray://…")`` (the ray client
proxy, ``python/ray/util/client/server``) while ``ray job submit`` runs
entrypoints through the dashboard's job module (SURVEY.md §1 layers 2/15,
§3.1; mount empty).

In this rebuild the daemon owns one ``DriverRuntime`` (cluster, raylets,
TPU scheduling data plane) and serves two client surfaces over
``ray_tpu.rpc``:

- **client mode** — the full task/actor/object API proxied for remote
  ``init(address=…)`` drivers.  Client-held objects deliberately take the
  worker-frame ownership model: the daemon never creates counted
  ObjectRefs for them (a transient server-side ref would hit zero when
  the handler returned and reclaim a result the client still holds).
- **operations** — status/memory/timeline introspection and job
  submission (``JobManager``), consumed by the CLI.
"""

from __future__ import annotations

import logging
import threading

from ..common.ids import ActorID, JobID, ObjectID, TaskID
from .serialization import deserialize, serialize


class HeadNode:
    def __init__(self, resources: dict | None = None,
                 num_workers: int | None = None,
                 system_config: dict | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 xlang_port: int | None = 0,
                 persist_path: str | None = None):
        """``persist_path`` enables head fault tolerance: the GCS
        metadata plane (KV incl. the job table, fn registry, named-actor
        specs) snapshots there periodically, and a restarted daemon
        restores it — agents reconnect and interrupted jobs re-run
        (reference: Redis-backed GCS FT, SURVEY.md §5.4; divergence
        noted in JobManager.restore_jobs)."""
        import os
        from .. import api
        from ..rpc import transport as _transport
        from ..rpc.xlang_gateway import XlangGateway
        from .job_manager import JobManager
        api.init(resources=resources, num_workers=num_workers,
                 system_config=system_config)
        self._rt = api._get_runtime()
        self._lock = threading.Lock()
        self.jobs = JobManager(self._rt.cluster.session_dir)
        self.jobs.attach_kv(self._rt.cluster.kv)
        self._persist_path = persist_path
        if persist_path and os.path.exists(persist_path):
            self._rt.cluster.restore_gcs_snapshot(persist_path)
        self.server = _transport.serve(self._handlers(), host=host,
                                       port=port)
        self.server.start()
        # cross-language surface (C++ frontend); xlang_port=None disables
        self.xlang = None if xlang_port is None else \
            XlangGateway(self._rt, host=host, port=xlang_port)
        self.jobs.head_address = self.server.address
        if self._rt.cluster.dashboard is not None:
            self._rt.cluster.dashboard.attach_jobs(self.jobs)
        # worker-node agents join through these handlers; attach also
        # serves the head's object plane (agents pull head-resident
        # objects from it)
        from .node_agent import AgentHub
        self.agent_hub = AgentHub(self._rt.cluster)
        self.agent_hub.attach(self.server)
        self._stop_event = threading.Event()
        # interrupted jobs re-run AFTER the control surface is up (their
        # drivers reconnect through it)
        if persist_path and os.path.exists(persist_path):
            self.jobs.head_address = self.server.address
            self.jobs.restore_jobs()
        self._persist_lock = threading.Lock()
        if persist_path:
            self._persist_thread = threading.Thread(
                target=self._persist_loop, daemon=True,
                name="head-persist")
            self._persist_thread.start()

    def _snapshot(self) -> None:
        with self._persist_lock:    # serialize vs the final stop save
            self._rt.cluster.save_gcs_snapshot(self._persist_path)

    def _persist_loop(self) -> None:
        log = logging.getLogger("ray_tpu.head")
        while not self._stop_event.wait(2.0):
            try:
                self._snapshot()
            except Exception:   # noqa: BLE001 — a failed snapshot must
                # not kill the daemon (next tick retries), but silent
                # persistence loss turns a later failover into data loss
                log.warning("gcs snapshot failed; retrying next tick",
                            exc_info=True)

    @property
    def address(self) -> str:
        return self.server.address

    def wait_for_shutdown(self, timeout: float | None = None) -> bool:
        return self._stop_event.wait(timeout)

    def stop(self) -> None:
        # stop jobs FIRST so their terminal statuses land in the final
        # snapshot — a job the operator shut down must not persist as
        # RUNNING and get resurrected by the next start
        self.jobs.stop_all(wait=True)
        if self._persist_path:
            try:    # final snapshot: clean stops restore losslessly
                self._stop_event.set()      # persist loop stands down
                self._snapshot()
            except Exception:   # noqa: BLE001
                pass
        self.agent_hub.shutdown()
        if self.xlang is not None:
            self.xlang.stop()
        self.server.stop()
        from .. import api
        api.shutdown()
        self._stop_event.set()

    # -- handler table -------------------------------------------------------
    def _handlers(self) -> dict:
        return {
            "ping": self._ping,
            "connect": self._connect,
            "fn_register": self._fn_register,
            "submit_spec": self._submit_spec,
            "get": self._get,
            "put": self._put,
            "wait": self._wait,
            "create_actor": self._create_actor,
            "submit_actor_call": self._submit_actor_call,
            "kill_actor": self._kill_actor,
            "get_actor_by_name": self._get_actor_by_name,
            "cancel": self._cancel,
            "kv": self._kv,
            "refs_flush": self._refs_flush,
            "client_bye": self._client_bye,
            "stream_wait": self._stream_wait,
            "stream_ack": self._stream_ack,
            "stream_close": self._stream_close,
            "status": self._status,
            "nodes": self._nodes,
            "drain_node": self._drain_node,
            "available_resources": self._available_resources,
            "cluster_resources": self._cluster_resources,
            "timeline": self._timeline,
            "state_list": self._state_list,
            "memory": self._memory,
            "worker_stacks": self._worker_stacks,
            "list_named_actors": self._list_named_actors,
            "request_resources": self._request_resources,
            "job_submit": self._job_submit,
            "job_status": self.jobs.status,
            "job_list": self.jobs.list,
            "job_logs": self.jobs.logs,
            "job_stop": self.jobs.stop,
            "stop_daemon": self._stop_async,
            "chaos": self._chaos,
            "rollout": self._rollout,
        }

    def _job_submit(self, *args, **kwargs) -> str:
        """Submit, then snapshot synchronously: a job acked by a
        persistent head must survive kill -9 right after the ack —
        the 2 s persist tick alone leaves a durability window where
        a restarted head has never heard of the job."""
        job_id = self.jobs.submit(*args, **kwargs)
        if self._persist_path:
            self._snapshot()
        return job_id

    def _rollout(self, op: str, deployment: str = "",
                 **kwargs) -> dict:
        """Model-version plane control/observe channel.  The rollout
        controller itself runs driver-side (it owns the serve app);
        the head exposes the KV-journaled registry — ``status`` — and
        the operator control flags — ``pause``/``resume``/``abort`` —
        that the driver-side controller polls between flips.  Because
        the journal lives in the GCS-snapshotted KV, a promoted
        standby serves the same view."""
        from ..versioning import VersionRegistry
        reg = VersionRegistry()
        if op == "status":
            if deployment:
                rec = reg.record(deployment)
                return {deployment: rec} if rec is not None else {}
            return reg.all()
        if op in ("pause", "abort"):
            reg.set_control(deployment, op)
            return {"deployment": deployment, "control": op}
        if op == "resume":
            reg.set_control(deployment, "")
            return {"deployment": deployment, "control": ""}
        raise ValueError(
            f"unknown rollout op {op!r} "
            f"(one of: status, pause, resume, abort)")

    def _chaos(self, op: str, **kwargs) -> dict:
        """Runtime control of the seeded network-chaos plane (shared
        dispatch with the CLI — ``rpc/chaos.py``)."""
        from ..rpc import chaos
        return chaos.control(op, **kwargs)

    # -- client-mode surface -------------------------------------------------
    def _ping(self) -> dict:
        return {"ok": True, "session_dir": self._rt.cluster.session_dir}

    def _request_resources(self, bundles: list[dict]) -> bool:
        asc = self._rt.cluster.autoscaler
        if asc is None:
            raise RuntimeError("no autoscaler is running on this head")
        asc.request_resources(bundles)
        return True

    def _list_named_actors(self, all_namespaces: bool = False,
                           namespace: str = "") -> list:
        """Filters by the CALLING client's namespace (it rides the
        RPC), never the head driver's."""
        ns = None if all_namespaces else (namespace or "")
        return self._rt.actor_manager.list_named(ns)

    def _worker_stacks(self, row: int | None = None,
                       timeout: float = 5.0) -> dict:
        """Live all-thread stacks of every worker (py-spy analogue —
        SURVEY §5.1(c)); keys serialized as 'row:index'."""
        got = self._rt.cluster.dump_worker_stacks(row=row,
                                                  timeout=timeout)
        return {f"{r}:{i}": text for (r, i), text in got.items()}

    def _connect(self, job_runtime_env: dict | None) -> dict:
        """A client attaches: allocate it a job id; a job-level env from
        the FIRST env-bearing client becomes the cluster default (one
        shared job env — the in-process simplification).  The client
        becomes a refcount HOLDER tied to this connection: its batched
        ref events fold under ("c", job) and a disconnect — graceful or
        abrupt — retires every count it held, so concurrent drivers have
        disjoint object lifetimes."""
        job_id = JobID.next()
        with self._lock:    # check-then-set: FIRST env-bearing client
            if job_runtime_env and not self._rt.cluster.job_runtime_env:
                self._rt.cluster.set_job_runtime_env(job_runtime_env)
        counter = self._rt.cluster.ref_counter
        am = self._rt.actor_manager

        def on_gone(job_bin=job_id.binary()):
            counter.holder_gone(("c", job_bin))
            # the job's EPHEMERAL actors die with it; detached survive
            am.on_job_exit(job_bin)
        self.server.on_conn_close(on_gone)
        return {"job_id": job_id.binary(),
                "session_dir": self._rt.cluster.session_dir}

    def _refs_flush(self, job_bin: bytes, events: list) -> None:
        self._rt.cluster.ref_counter.apply_batch(events, ("c", job_bin))

    def _client_bye(self, job_bin: bytes) -> None:
        self._rt.cluster.ref_counter.holder_gone(("c", job_bin))

    def _stream_wait(self, task_bin: bytes, index: int,
                     timeout: float | None):
        sealed, done, error, known = self._rt.stream_wait(
            TaskID(task_bin), index, timeout)
        return (sealed, done, serialize(error) if error else None,
                known)

    def _stream_ack(self, task_bin: bytes, consumed: int) -> None:
        self._rt.stream_ack(TaskID(task_bin), consumed)

    def _stream_close(self, task_bin: bytes, consumed: int) -> None:
        self._rt.stream_close(TaskID(task_bin), consumed)

    def _fn_register(self, fn_id: str, fn_bytes: bytes) -> None:
        self._rt.fn_registry.setdefault(fn_id, fn_bytes)

    def _submit_spec(self, spec_bytes: bytes, fn_id: str,
                     fn_bytes: bytes | None,
                     job_bin: bytes | None = None) -> None:
        from .object_ref import counter_suppressed
        # suppressed: counted server-side twins of the client's refs
        # would decref to zero on lineage eviction and reclaim objects
        # the client still holds (see counter_suppressed docstring)
        with counter_suppressed():
            spec = deserialize(spec_bytes)
        if job_bin is not None:
            counter = self._rt.cluster.ref_counter
            for i in range(spec.num_returns):
                counter.set_owner(
                    ObjectID.for_task_return(spec.task_id, i + 1),
                    ("c", job_bin))
        self._rt.submit_spec(spec, fn_id, fn_bytes)

    def _get(self, oid_bins: list[bytes], timeout: float | None):
        oids = [ObjectID(b) for b in oid_bins]
        try:
            return ("ok", serialize(self._rt.get_raw(oids, timeout)))
        except BaseException as e:      # noqa: BLE001 — typed re-raise
            return ("exc", serialize(e))    # client-side

    def _put(self, value_bytes: bytes, job_bin: bytes | None = None,
             contained: list | None = None) -> bytes:
        from .object_ref import counter_suppressed
        with counter_suppressed():      # see _submit_spec
            value = deserialize(value_bytes)
        oid = self._rt.put_raw(value)
        counter = self._rt.cluster.ref_counter
        if job_bin is not None:
            counter.set_owner(oid, ("c", job_bin))
        if contained:
            counter.add_contained(oid,
                                  [ObjectID(b) for b in contained])
        return oid.binary()

    def _wait(self, oid_bins: list[bytes], num_returns: int,
              timeout: float | None):
        ready, not_ready = self._rt.wait_raw(
            [ObjectID(b) for b in oid_bins], num_returns, timeout)
        return ([o.binary() for o in ready],
                [o.binary() for o in not_ready])

    def _create_actor(self, actor_bin: bytes, cls_id: str,
                      cls_bytes: bytes | None, payload: bytes) -> None:
        from .object_ref import counter_suppressed
        with counter_suppressed():      # see _submit_spec
            unpacked = deserialize(payload)
        namespace, lifetime = "", None
        if len(unpacked) == 11:
            (args, kwargs, max_restarts, max_task_retries, name, res,
             strategy, runtime_env, concurrency, namespace,
             lifetime) = unpacked
        elif len(unpacked) == 9:
            (args, kwargs, max_restarts, max_task_retries, name, res,
             strategy, runtime_env, concurrency) = unpacked
        else:               # pre-concurrency client
            (args, kwargs, max_restarts, max_task_retries, name, res,
             strategy, runtime_env) = unpacked
            concurrency = None
        self._rt.create_actor(ActorID(actor_bin), cls_id, cls_bytes,
                              args, kwargs, max_restarts,
                              max_task_retries, name, resources=res,
                              strategy=strategy, runtime_env=runtime_env,
                              concurrency=concurrency,
                              namespace=namespace, lifetime=lifetime)

    def _submit_actor_call(self, actor_bin: bytes, task_bin: bytes,
                           method: str, payload: bytes,
                           num_returns: int) -> None:
        from .object_ref import counter_suppressed
        with counter_suppressed():      # see _submit_spec
            unpacked = deserialize(payload)
        if len(unpacked) == 4:
            args, kwargs, trace_ctx, group = unpacked
        else:
            args, kwargs, trace_ctx = unpacked
            group = None
        self._rt.actor_manager.submit(
            ActorID(actor_bin), TaskID(task_bin), method, args, kwargs,
            num_returns, trace_ctx=trace_ctx, concurrency_group=group)

    def _kill_actor(self, actor_bin: bytes, no_restart: bool) -> None:
        self._rt.actor_manager.kill(ActorID(actor_bin),
                                    no_restart=no_restart)

    def _get_actor_by_name(self, name: str,
                           namespace: str = "") -> bytes | None:
        aid = self._rt.actor_manager.get_by_name(name, namespace)
        return aid.binary() if aid is not None else None

    def _cancel(self, task_bin: bytes, force: bool) -> None:
        # cluster-wide: the task may be queued/running/agent-leased on
        # any node, not just the head's raylet
        self._rt.cluster.cancel_task(TaskID(task_bin), force=force)

    def _kv(self, op: str, key: bytes, value: bytes | None,
            namespace: str, overwrite: bool):
        return self._rt.cluster.kv.dispatch(op, key, value, namespace,
                                            overwrite)

    # -- operations surface --------------------------------------------------
    def _status(self) -> dict:
        from .. import api
        cluster = self._rt.cluster
        return {
            "address": self.address,
            "role": "primary",
            "leasing": self._leasing_stats(),
            "xlang_address": self.xlang.address if self.xlang else None,
            "dashboard_url": (cluster.dashboard.url
                              if cluster.dashboard else None),
            "session_dir": cluster.session_dir,
            "nodes": api.nodes(),
            "available_resources": api.available_resources(),
            "cluster_resources": api.cluster_resources(),
            "store": cluster.store.stats(),
            "object_plane": cluster.plane.stats(),
            "pulls": cluster.pull_manager.stats(),
            "broadcasts": cluster.broadcasts.stats(),
            "jobs": self.jobs.list(),
            "drains": cluster.drain_status(),
            "serve": self._serve_stats(),
            "train": self._train_stats(cluster),
            "versions": self._version_stats(),
            "health": self._health_stats(cluster),
            "chaos": self._chaos_stats(),
        }

    @staticmethod
    def _leasing_stats() -> dict:
        try:
            from ..leasing import aggregate_stats
            return aggregate_stats()
        except Exception:   # noqa: BLE001 — lease plane disabled
            return {}

    @staticmethod
    def _train_stats(cluster) -> dict:
        # elastic training plane: driver-local run gauges plus the
        # loan manager's two-directional lending counters
        out: dict = {}
        try:
            from ..train.elastic import active_train_stats
            runs = active_train_stats()
            if runs:
                out["runs"] = runs
        except Exception:   # noqa: BLE001 — train plane unused
            pass
        loans = getattr(cluster, "loans", None)
        if loans is not None:
            try:
                out["loans"] = loans.stats()
            except Exception:   # noqa: BLE001
                pass
        return out

    @staticmethod
    def _health_stats(cluster) -> dict:
        from ..rpc import breaker
        health = getattr(cluster, "health", None)
        out = health.stats() if health is not None else {}
        out["suspect_rows"] = cluster.crm.suspect_rows()
        out["breakers"] = breaker.stats()
        return out

    @staticmethod
    def _chaos_stats() -> dict:
        from ..rpc import chaos
        return chaos.status() if chaos.is_enabled() else {"enabled": False}

    @staticmethod
    def _version_stats() -> dict:
        # per-deployment model-version journal (current version plus
        # any in-flight rollout's phase/progress); empty when the
        # version registry has never been written
        try:
            from ..versioning import VersionRegistry
            out = {}
            for name, rec in VersionRegistry().all().items():
                row = {"current": rec["current"],
                       "previous": rec["previous"]}
                ro = rec.get("rollout")
                if ro is not None:
                    row["rollout"] = {
                        "to": ro["to"], "phase": ro["phase"],
                        "flipped": ro["flipped"],
                        "replicas": ro["replicas"],
                        "error": ro["error"]}
                out[name] = row
            return out
        except Exception:   # noqa: BLE001 — versioning absent/unused
            return {}

    @staticmethod
    def _serve_stats() -> dict:
        # per-deployment request-plane stats; only populated when serve
        # apps run in this process (the router registry is local)
        try:
            from ..serve.router import request_plane_stats
            return request_plane_stats()
        except Exception:   # noqa: BLE001 — serve absent/unused
            return {}

    def _nodes(self) -> list[dict]:
        from .. import api
        return api.nodes()

    def _drain_node(self, node_id_hex: str, reason: str = "",
                    deadline_s: float | None = None) -> dict:
        from ..common.ids import NodeID
        return self._rt.cluster.drain_node(
            NodeID.from_hex(node_id_hex), reason=reason,
            deadline_s=deadline_s)

    def _available_resources(self) -> dict:
        from .. import api
        return api.available_resources()

    def _cluster_resources(self) -> dict:
        from .. import api
        return api.cluster_resources()

    def _timeline(self) -> list[dict]:
        return self._rt.cluster.events.timeline()

    def _state_list(self, kind: str,
                    filters: list | None = None) -> list[dict]:
        """State-API rows for the CLI (reference: ``ray list tasks`` et
        al. resolve through the head's state aggregator)."""
        from ..util import state
        table = {"tasks": state.list_tasks,
                 "actors": state.list_actors,
                 "objects": state.list_objects,
                 "nodes": state.list_nodes,
                 "placement-groups": state.list_placement_groups}
        fn = table.get(kind)
        if fn is None:
            raise ValueError(
                f"unknown state kind {kind!r} (one of {sorted(table)})")
        return fn([tuple(f) for f in filters] if filters else None)

    def _memory(self) -> dict:
        return self._rt.cluster.store.stats()

    def _stop_async(self) -> str:
        # reply first, THEN tear down — stopping inline would close the
        # socket under the caller's pending reply
        threading.Timer(0.2, self.stop).start()
        return "stopping"
