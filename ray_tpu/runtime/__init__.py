"""Host runtime: object store, worker pool, raylet, task management.

The control plane of the framework (SURVEY.md §1 layers 4/6/7/9 — raylet,
object store, core worker, Python API).  Device math lives in ray_tpu/ops;
everything here is host-side orchestration around it.
"""

from .object_directory import ObjectDirectory
from .object_ref import ObjectRef
from .object_store import MemoryStore, ObjectLostError, GetTimeoutError
from .pull_manager import PullManager, PullPriority
from .serialization import RayTaskError, WorkerCrashedError

__all__ = ["ObjectDirectory", "ObjectRef", "MemoryStore", "ObjectLostError",
           "GetTimeoutError", "PullManager", "PullPriority", "RayTaskError",
           "WorkerCrashedError"]
