"""Structured event log + timeline (observability floor).

Reference parity: upstream emits structured per-component logs under the
session's ``logs/`` dir and records task lifecycle events that
``ray.timeline()`` exports as a Chrome trace (``src/ray/util/event.cc``,
``python/ray/_private/state.py::timeline`` — SURVEY.md §1 layer 12,
§5.5; mount empty).

One process-local sink serves both: ``emit()`` appends a JSON line to
``<log_dir>/events.jsonl`` (structured logs) and keeps a bounded
in-memory ring of timeline spans that exports in Chrome
``chrome://tracing`` format.  Gated by ``event_log_enabled``; the file
sink lazily creates ``log_dir`` (config, else ``<session>/logs``).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

from ..common.config import get_config
from ..common import clock as _clk

_RING = 65536           # bounded timeline memory (spans)


class EventLog:
    def __init__(self, session_dir: str):
        cfg = get_config()
        self.enabled = cfg.event_log_enabled
        self._dir = cfg.log_dir or os.path.join(session_dir, "logs")
        self._lock = threading.Lock()
        self._file = None
        self._ring: deque = deque(maxlen=_RING)
        self.num_events = 0

    # -- structured log ------------------------------------------------------
    def emit(self, category: str, name: str, **fields) -> None:
        """Append one structured event (JSON line) and record it in the
        timeline ring.  No-op when ``event_log_enabled`` is false."""
        if not self.enabled:
            return
        ev = {"ts": _clk.now(), "category": category, "name": name,
              **fields}
        with self._lock:
            self.num_events += 1
            self._ring.append(ev)
            try:
                if self._file is None:
                    os.makedirs(self._dir, exist_ok=True)
                    self._file = open(
                        os.path.join(self._dir, "events.jsonl"), "a",
                        buffering=1)
                self._file.write(json.dumps(ev) + "\n")
            except OSError:
                self._file = None       # disk trouble: keep the ring only

    def span(self, category: str, name: str, start: float, end: float,
             node_row: int, **fields) -> None:
        """Record a completed duration span (timeline 'X' event)."""
        if not self.enabled:
            return
        ev = {"ts": start, "dur": end - start, "category": category,
              "name": name, "node_row": node_row, **fields}
        with self._lock:
            self.num_events += 1
            self._ring.append(ev)

    # -- timeline export -----------------------------------------------------
    def timeline(self) -> list[dict]:
        """Chrome-trace events (``chrome://tracing`` / Perfetto load this
        directly, like the reference's ``ray.timeline()``)."""
        with self._lock:
            events = list(self._ring)
        out = []
        for ev in events:
            base = {
                "name": ev["name"],
                "cat": ev["category"],
                "pid": ev.get("node_row", 0),
                "tid": ev.get("worker", 0),
                "ts": ev["ts"] * 1e6,           # chrome wants microseconds
                "args": {k: v for k, v in ev.items()
                         if k not in ("ts", "dur", "category", "name")},
            }
            if "dur" in ev:
                base["ph"] = "X"
                base["dur"] = ev["dur"] * 1e6
            else:
                base["ph"] = "i"                # instant
                base["s"] = "g"
            out.append(base)
        return out

    def dump_timeline(self, filename: str) -> str:
        with open(filename, "w") as f:
            json.dump(self.timeline(), f)
        return filename

    def close(self) -> None:
        with self._lock:
            self.enabled = False    # a late emit must not recreate the
            #                         log dir inside a deleted session
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def stats(self) -> dict:
        with self._lock:
            return {"num_events": self.num_events,
                    "ring_size": len(self._ring),
                    "log_dir": self._dir}
