"""Raylet: per-node task queueing, scheduling, dispatch, and completion.

Reference parity: the raylet's ``NodeManager`` + ``ClusterTaskManager``
(queue by scheduling class, schedule per event-loop turn) +
``LocalTaskManager`` (resource allocation + worker handout) +
``DependencyManager`` (hold tasks until args exist) — ``src/ray/raylet/``,
SURVEY.md §1 layer 4 / §3.2 hot loop; mount empty.

Single-process form: one Raylet owns the local ``ClusterResourceManager``
row, a ``WorkerPool`` of spawned processes, and the in-process object
store.  The scheduling loop is an event-driven thread (condition variable,
not a busy tick): it wakes on task arrival, dependency readiness, worker
release, and resource release — the same wake set as the reference's asio
event loop.  The simulated multi-node harness instantiates N of these over
one shared resource view.
"""

from __future__ import annotations

import threading
from collections import deque

from ..common.ids import TaskID
from ..common.resources import ResourceRequest
from ..common.task_spec import TaskSpec
from ..scheduling.cluster_resources import ClusterResourceManager
from .object_ref import ObjectRef
from .object_store import MemoryStore
from .serialization import (RayTaskError, WorkerCrashedError, deserialize,
                            serialize)
from .task_manager import TaskManager
from .worker_pool import WorkerHandle, WorkerPool


class Raylet:
    def __init__(self, node_id, crm: ClusterResourceManager,
                 store: MemoryStore, num_workers: int,
                 fn_registry: dict[str, bytes]):
        self.node_id = node_id
        self.crm = crm
        self.row = crm.row_of(node_id)
        self.store = store
        self.task_manager = TaskManager()
        self._fn_registry = fn_registry
        self._cv = threading.Condition()
        self._queue: deque[TaskID] = deque()
        self._waiting: dict[TaskID, int] = {}   # task -> missing dep count
        self._running: dict[bytes, tuple[TaskID, WorkerHandle]] = {}
        self._stopped = False
        self._dirty = False     # wake flag: new task / capacity / worker
        self.actor_manager = None   # attached by the driver runtime
        self.pool = WorkerPool(num_workers, self._on_worker_message,
                               self._on_worker_death,
                               on_idle=self._notify_dirty)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"raylet-{self.row}")

    def start(self) -> None:
        self.pool.start()
        self._thread.start()

    # -- submission ---------------------------------------------------------
    def submit(self, spec: TaskSpec) -> list[ObjectRef]:
        rec = self.task_manager.register(spec)
        deps = [a.id for a in spec.args if isinstance(a, ObjectRef)]
        missing = [d for d in deps if not self.store.contains(d)]
        if missing:
            with self._cv:
                self._waiting[spec.task_id] = len(missing)
            for d in missing:
                self.store.on_ready(d, lambda _oid, t=spec.task_id:
                                    self._dep_ready(t))
        else:
            self._enqueue(spec.task_id)
        return [ObjectRef(oid) for oid in rec.return_ids]

    def _dep_ready(self, task_id: TaskID) -> None:
        with self._cv:
            left = self._waiting.get(task_id)
            if left is None:
                return
            if left <= 1:
                del self._waiting[task_id]
                self._queue.append(task_id)
                self._dirty = True
                self._cv.notify_all()
            else:
                self._waiting[task_id] = left - 1

    def _enqueue(self, task_id: TaskID) -> None:
        with self._cv:
            self._queue.append(task_id)
            self._dirty = True
            self._cv.notify_all()

    def _notify_dirty(self) -> None:
        with self._cv:
            self._dirty = True
            self._cv.notify_all()

    # -- scheduling loop ----------------------------------------------------
    def _loop(self) -> None:
        """Event-driven: wakes only when the dirty flag was raised (task
        arrival, dep readiness, worker idle, resources freed) — a leftover
        queue alone does NOT re-trigger, so an unplaceable backlog parks
        instead of busy-spinning."""
        while True:
            with self._cv:
                while not self._stopped and not (self._dirty and self._queue):
                    self._cv.wait()
                if self._stopped:
                    return
                self._dirty = False
                batch = list(self._queue)
                self._queue.clear()
            leftover = self._dispatch_batch(batch)
            if leftover:
                with self._cv:
                    # keep arrival order: leftovers go back to the front
                    self._queue.extendleft(reversed(leftover))

    def _dispatch_batch(self, batch: list[TaskID]) -> list[TaskID]:
        leftover: list[TaskID] = []
        for i, task_id in enumerate(batch):
            rec = self.task_manager.get(task_id)
            if rec is None or rec.done:
                continue
            spec = rec.spec
            # reserve resources BEFORE popping a worker: pool.release fires
            # the idle wake-up, so a speculative pop-then-release of the
            # same worker would spin the loop on an unplaceable backlog
            if not self.crm.subtract(self.row, spec.resources):
                leftover.append(task_id)
                continue
            worker = self.pool.pop_idle()
            if worker is None:
                self.crm.add_back(self.row, spec.resources)
                leftover.append(task_id)
                leftover.extend(batch[i + 1:])
                break
            if not self._dispatch(worker, rec):
                # dep error or send failure; resources already returned
                continue
        return leftover

    def _dispatch(self, worker: WorkerHandle, rec) -> bool:
        spec = rec.spec
        # resolve top-level ObjectRef args (deps are ready by construction)
        args = []
        dep_error = None
        for a in spec.args:
            if isinstance(a, ObjectRef):
                v = self.store.peek(a.id)
                if isinstance(v, RayTaskError):
                    dep_error = v
                    break
                args.append(v)
            else:
                args.append(a)
        if dep_error is not None:
            # propagate the dependency's error to this task's outputs
            # without executing (reference: failed deps fail the task)
            self._finish_with_error(rec, dep_error, worker)
            return False

        fn_id = spec.function_descriptor
        if fn_id not in worker.fn_cache:
            if not worker.send(("fn", fn_id, self._fn_registry[fn_id])):
                self._requeue_after_worker_loss(rec, worker)
                return False
            worker.fn_cache.add(fn_id)
        payload = serialize((tuple(args), spec.kwargs, spec.num_returns))
        worker.leased_task = spec.task_id.binary()
        with self._cv:
            self._running[spec.task_id.binary()] = (spec.task_id, worker)
        if not worker.send(("exec", spec.task_id.binary(), fn_id, payload)):
            with self._cv:
                self._running.pop(spec.task_id.binary(), None)
            self._requeue_after_worker_loss(rec, worker)
            return False
        return True

    def _requeue_after_worker_loss(self, rec, worker: WorkerHandle) -> None:
        self.crm.add_back(self.row, rec.spec.resources)
        worker.dead = True
        self._enqueue(rec.spec.task_id)

    def _finish_with_error(self, rec, error: RayTaskError,
                           worker: WorkerHandle | None) -> None:
        self.task_manager.complete(rec.spec.task_id)
        for oid in rec.return_ids:
            self.store.put(oid, error)
        self.crm.add_back(self.row, rec.spec.resources)
        if worker is not None:
            self.pool.release(worker)
        self._notify_dirty()

    # -- worker frame handling (runs on reader threads) ---------------------
    def _on_worker_message(self, worker: WorkerHandle, msg: tuple) -> None:
        kind = msg[0]
        am = self.actor_manager
        if am is not None:
            if am.on_worker_message(worker, msg):
                return
            if kind == "actor_create":
                from ..common.ids import ActorID
                args, kwargs, max_restarts, max_task_retries, name = \
                    deserialize(msg[4])
                am.create_actor(ActorID(msg[1]), msg[2], msg[3], args,
                                kwargs, max_restarts, max_task_retries, name)
                return
            if kind == "actor_submit":
                from ..common.ids import ActorID
                args, kwargs, num_returns = deserialize(msg[4])
                am.submit(ActorID(msg[1]), TaskID(msg[2]), msg[3], args,
                          kwargs, num_returns)
                return
            if kind == "actor_kill":
                from ..common.ids import ActorID
                am.kill(ActorID(msg[1]), no_restart=msg[2])
                return
            if kind == "named_actor":
                aid = am.get_by_name(msg[1])
                worker.send(("named_actor_reply",
                             aid.binary() if aid else None))
                return
        if kind in ("result", "error"):
            task_id_bin = msg[1]
            with self._cv:
                entry = self._running.pop(task_id_bin, None)
            if entry is None:
                self.pool.release(worker)
                return
            task_id, _ = entry
            rec = self.task_manager.complete(task_id)
            if rec is not None:
                if kind == "result":
                    for oid, data in zip(rec.return_ids, msg[2]):
                        self.store.put(oid, deserialize(data))
                else:
                    err = deserialize(msg[2])
                    for oid in rec.return_ids:
                        self.store.put(oid, err)
                self.crm.add_back(self.row, rec.spec.resources)
            self.pool.release(worker)
            self._notify_dirty()
        elif kind == "get":
            oids = [self._oid(b) for b in msg[1]]
            timeout = msg[2] if len(msg) > 2 else None
            if all(self.store.contains(o) for o in oids):
                worker.send(("get_reply", serialize(
                    ("ok", self.store.get_raw_blocking(oids)))))
                return
            # Blocking get: release the task's resources while the worker
            # waits (reference: CPU is returned during ray.get so dependent
            # tasks can run) and grow the pool if it is starved — otherwise
            # recursive fan-out deadlocks on worker slots.
            rec = None
            if worker.leased_task is not None:
                with self._cv:
                    entry = self._running.get(worker.leased_task)
                if entry is not None:
                    rec = self.task_manager.get(entry[0])
            worker.blocked = True
            if rec is not None:
                self.crm.add_back(self.row, rec.spec.resources)
                self._notify_dirty()
            self.pool.grow_for_blocked()
            values = self.store.get_raw_blocking(oids, timeout=timeout)
            # re-acquire before resuming (waits for capacity like the
            # reference's worker unblock path; bounded oversubscription is
            # preferred over a stuck reader if capacity never frees)
            if rec is not None:
                self._reacquire(rec.spec.resources)
            worker.blocked = False
            if values is None:
                worker.send(("get_reply", serialize(("timeout", None))))
            else:
                worker.send(("get_reply", serialize(("ok", values))))
        elif kind == "put":
            self.store.put(self._oid(msg[1]), deserialize(msg[2]))
        elif kind == "submit":
            spec = deserialize(msg[1])
            fn_id, fn_bytes = msg[2], msg[3]
            if fn_bytes is not None and fn_id not in self._fn_registry:
                self._fn_registry[fn_id] = fn_bytes
            self.submit(spec)

    @staticmethod
    def _oid(binary: bytes):
        from ..common.ids import ObjectID
        return ObjectID(binary)

    def _reacquire(self, resources: ResourceRequest,
                   patience: float = 5.0) -> None:
        import time
        deadline = time.monotonic() + patience
        while not self.crm.subtract(self.row, resources):
            if time.monotonic() >= deadline:
                # oversubscribe rather than wedge: force the debit so the
                # books stay balanced when the task completes
                self.crm.force_subtract(self.row, resources)
                return
            time.sleep(0.002)

    def _on_worker_death(self, worker: WorkerHandle) -> None:
        if self.actor_manager is not None and \
                self.actor_manager.on_worker_death(worker):
            return
        task_id_bin = worker.leased_task
        if task_id_bin is None:
            return
        with self._cv:
            entry = self._running.pop(task_id_bin, None)
        if entry is None:
            return
        task_id, _ = entry
        rec = self.task_manager.get(task_id)
        if rec is None:
            return
        self.crm.add_back(self.row, rec.spec.resources)
        if self.task_manager.should_retry(task_id):
            self._enqueue(task_id)
        else:
            self.task_manager.complete(task_id)
            err = RayTaskError(
                rec.spec.function_descriptor,
                "worker died", WorkerCrashedError(
                    f"worker {worker.index} died executing "
                    f"{rec.spec.function_descriptor}"))
            for oid in rec.return_ids:
                self.store.put(oid, err)
        self._notify_dirty()

    # -- cancel / teardown --------------------------------------------------
    def cancel(self, task_id: TaskID, force: bool = False) -> bool:
        from .serialization import TaskCancelledError
        with self._cv:
            if task_id in self._queue:
                self._queue.remove(task_id)
                rec = self.task_manager.complete(task_id)
                if rec:
                    err = RayTaskError(rec.spec.function_descriptor,
                                       "cancelled", TaskCancelledError())
                    for oid in rec.return_ids:
                        self.store.put(oid, err)
                return True
            entry = self._running.get(task_id.binary())
        if entry is not None and force:
            _, worker = entry
            self.pool.kill_worker(worker)   # death path handles bookkeeping
            return True
        return False

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self.pool.shutdown()
