"""Raylet: per-node task queueing, scheduling, dispatch, and completion.

Reference parity: the raylet's ``NodeManager`` + ``ClusterTaskManager``
(queue by scheduling class, schedule per event-loop turn via
``ClusterResourceScheduler::GetBestSchedulableNode``) + ``LocalTaskManager``
(resource allocation + worker handout) + ``DependencyManager`` (hold tasks
until args exist) + spillback to the chosen remote raylet — ``src/ray/
raylet/``, SURVEY.md §1 layer 4 / §3.2 hot loop; mount empty.

TPU-first: when a scheduling round's batch is big enough and uniformly
default-strategy, the WHOLE batch is placed by the device water-fill kernel
(``ray_tpu.ops.schedule_grouped``) in one call — the north-star data plane
running inside the live runtime.  Small or mixed batches take the per-task
CPU policy, which is bit-identical by the parity contract, so the switch is
invisible to callers (``scheduler_device_backend`` config).

The scheduling loop is event-driven (condition variable, not a busy tick):
it wakes on task arrival, dependency readiness, worker release, and
resource release — the same wake set as the reference's asio event loop.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

from ..common.config import get_config
from ..common.ids import TaskID
from ..common.resources import ResourceRequest
from ..common.task_spec import SchedulingStrategyKind
from ..scheduling.policy import (CompositeSchedulingPolicy,
                                 SchedulingOptions, SchedulingType)
from .object_ref import ObjectRef
from .serialization import (RayTaskError, WorkerCrashedError, deserialize,
                            serialize)
from .worker_pool import WorkerHandle, WorkerPool
from ..common import clock as _clk


class _ClassQueue:
    """Dispatch queue bucketed by scheduling-class key, FIFO per class
    (the reference ClusterTaskManager keys its dispatch queues by
    SchedulingClass — SURVEY.md §1 layer 4).  All mutation runs under
    the owning raylet's ``_cv``; iteration order is class insertion
    order, FIFO within a class."""

    __slots__ = ("_by", "_key_of")

    def __init__(self):
        self._by: dict = {}             # class key -> deque[TaskID]
        self._key_of: dict = {}         # TaskID -> class key

    def append(self, task_id, key=None) -> None:
        self._key_of[task_id] = key
        dq = self._by.get(key)
        if dq is None:
            dq = self._by[key] = deque()
        dq.append(task_id)

    def remove(self, task_id) -> None:
        """Raises ValueError when absent (deque.remove contract)."""
        try:
            key = self._key_of.pop(task_id)
        except KeyError:
            raise ValueError(task_id) from None
        dq = self._by[key]
        dq.remove(task_id)
        if not dq:
            del self._by[key]

    def classes(self) -> list:
        return list(self._by)

    def bucket(self, key):
        return self._by.get(key, ())

    def clear(self) -> None:
        self._by.clear()
        self._key_of.clear()

    def __contains__(self, task_id) -> bool:
        return task_id in self._key_of

    def __len__(self) -> int:
        return len(self._key_of)

    def __bool__(self) -> bool:
        return bool(self._key_of)

    def __iter__(self):
        for dq in list(self._by.values()):
            yield from list(dq)


class Raylet:
    def __init__(self, node_id, cluster, num_workers: int,
                 spawner=None, inline_objects: bool = False,
                 plane_address: str | None = None):
        self.node_id = node_id
        self.cluster = cluster
        # remote-node raylet: workers live on another machine (node
        # agent) and share no arena with the head.  The agent always
        # runs its own arena (plane_address mandatory for remote
        # nodes): plasma args/results move over the object plane and
        # frames carry by-REFERENCE descriptors the agent resolves
        # against its local store.  inline_objects marks the no-shared-
        # arena transport (small values still ship in-band in frames)
        self.inline_objects = inline_objects
        self.plane_address = plane_address
        self.remote_plane = plane_address is not None
        self.crm = cluster.crm
        self.row = self.crm.row_of(node_id)
        self.store = cluster.store
        self.task_manager = cluster.task_manager
        self._fn_registry = cluster.fn_registry
        self._policy = CompositeSchedulingPolicy()
        self._cv = threading.Condition()
        self._queue: deque[TaskID] = deque()        # awaiting PLACEMENT
        self._local_queue = _ClassQueue()   # placed here, await dispatch
        self._planned_cu = None     # dense planned-load vector (lazy width)
        self._waiting: dict[TaskID, int] = {}   # task -> missing dep count
        self._pull_pending: dict[TaskID, int] = {}  # task -> in-flight pulls
        # task_id_bin -> (TaskID, WorkerHandle, pinned shm-arg batch)
        self._running: dict[bytes, tuple[TaskID, WorkerHandle, list]] = {}
        self._task_start: dict[bytes, float] = {}   # timeline spans
        self._round_durations: deque = deque(maxlen=256)    # metrics p50
        self._local_since: dict[TaskID, float] = {}  # lease-wait clocks
        # first time a task missed pop_idle for its runtime env (grace
        # for env-worker growth is measured from HERE, not queue entry —
        # a task long-queued for unrelated reasons must still wait out
        # the grace before the cache grows)
        self._env_miss_since: dict[TaskID, float] = {}
        self._env_staging: set[str] = set()     # env keys staging off-thread
        # count of pipelined-lease entries across all workers: while
        # nonzero the event loop wakes periodically to reconcile
        # entries stranded by commit races (worker released/blocked/
        # died between pipeline_target and the commit)
        self._assigned_total = 0
        self._avoid_local: set[TaskID] = set()  # lease-spilled: skip here
        # last _effective_snapshot soft-masked SUSPECT rows out (gray
        # failures): tells _schedule_rows a -1 deserves a full-cluster
        # fallback pass before parking the task
        self._suspect_softmask = False
        # device-resident delta-heartbeat engine (lazy: only rounds that
        # take the plain device path ever build one)
        self._delta_engine = None
        self._stopped = False
        # DRAINING: no new leases commit here, running tasks finish;
        # the pool and event loop stay alive (unlike _stopped) so the
        # node keeps scheduling its backlog onto OTHER rows
        self._draining = False
        self._dirty = False     # wake flag: new task / capacity / worker
        self.actor_manager = None   # attached by the runtime/cluster
        # agent-autonomous dispatch bookkeeping (plane agents only):
        # tasks the AGENT leased locally without a head round-trip —
        # registered here via the batched agent_sync so lineage,
        # ownership, and node-death recovery still work
        self.agent_inflight: dict = {}          # TaskID -> TaskRecord
        self.agent_local_cu: dict | None = None  # live local demand
        arena = getattr(cluster, "arena", None)
        self.pool = WorkerPool(
            num_workers, self._on_worker_message, self._on_worker_death,
            on_idle=self._notify_dirty,
            arena_path=(arena.path if arena and not inline_objects
                        else None),
            spawner=spawner)
        self.pool.node_id_hex = node_id.hex()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"raylet-{self.row}")

    def start(self) -> None:
        self.pool.start()
        self._thread.start()

    # -- submission ---------------------------------------------------------
    def submit(self, spec) -> None:
        """Register + enter scheduling.  Deliberately returns NO
        ObjectRefs: result refs are the caller's to create BEFORE
        submitting (owner-side refcounting — a transient ref made here
        and dropped would dip the count to zero and could reclaim the
        result under a caller that has not built its refs yet)."""
        job_env = self.cluster.job_runtime_env
        if job_env:
            from .runtime_env import merge_runtime_env
            spec.runtime_env = merge_runtime_env(job_env, spec.runtime_env)
        self.submit_existing(self.task_manager.register(spec))

    def submit_existing(self, rec) -> None:
        """(Re-)enter an already-registered task into scheduling — the
        lineage-reconstruction resubmit path shares this with first
        submission (reference: reconstruction re-drives the normal task
        path with attempt_number bumped)."""
        spec = rec.spec
        deps = [a.id for a in spec.args if isinstance(a, ObjectRef)]
        missing = [d for d in deps if not self.store.contains(d)]
        if missing:
            with self._cv:
                self._waiting[spec.task_id] = len(missing)
            for d in missing:
                self.store.on_ready(d, lambda _oid, t=spec.task_id:
                                    self._dep_ready(t))
        else:
            self._enqueue(spec.task_id)

    def enqueue_forwarded(self, task_id: TaskID) -> None:
        """Arrival needing (re-)placement (deps already resolved)."""
        self._enqueue(task_id)

    # -- autoscaler hooks ----------------------------------------------------
    def pending_demand(self) -> list:
        """Resource requests of tasks awaiting placement here (infeasible
        parks) AND placed-but-undispatched backlog (resource-starved local
        queue) — the raylet's share of autoscaler demand (reference:
        LoadMetrics resource_load_by_shape includes both).  Local backlog
        is safe to report: the packing pass fits demand onto existing free
        capacity first, so only genuinely-starved tasks launch nodes."""
        with self._cv:
            ids = list(self._queue) + list(self._local_queue)
        out = []
        for tid in ids:
            rec = self.task_manager.get(tid)
            if rec is not None and not rec.done:
                out.append(rec.spec.resources)
        return out

    def is_idle(self) -> bool:
        """No queued, waiting, placed, or running work on this node
        (including tasks its agent leased autonomously)."""
        with self._cv:
            return not (self._queue or self._local_queue or self._running
                        or self._waiting or self._pull_pending
                        or self.agent_inflight)

    def queue_stats(self) -> dict:
        """Live depths + recent scheduling-round durations (metrics)."""
        with self._cv:
            return {"pending": len(self._queue) + len(self._waiting),
                    "placed": len(self._local_queue),
                    "running": len(self._running),
                    "round_durations": list(self._round_durations)}

    # -- health (GCS health-check manager probes this) -----------------------
    def ping(self) -> None:
        """Health ping: wake the event loop so it re-stamps its pong
        (reference: the raylet answering the GCS health-check RPC proves
        its main loop turns)."""
        self._notify_dirty()

    @property
    def last_pong(self) -> float:
        return getattr(self, "_last_pong", 0.0)

    def health_vitals(self) -> dict:
        """Structural liveness (the manager decides staleness by comparing
        ``last_pong`` against its own previous ping time)."""
        return {
            "thread_alive": self._thread.is_alive(),
            "workers_alive": (self.pool.num_alive() > 0
                              or self.pool.expected() == 0),
            "last_pong": self.last_pong,
        }

    def enqueue_local(self, task_id: TaskID) -> None:
        """Placement decided: this node owns the task until dispatch.

        Tasks are scheduled ONCE (reference: ClusterTaskManager places,
        then the task waits in LocalTaskManager for workers/resources —
        it is not re-scheduled on every worker event).  The planned load
        is visible to subsequent scheduling rounds so they do not
        over-assign this node.  Plasma args not yet local are pulled at
        task-arg priority; dispatch waits for the copies (reference:
        DependencyManager asks the PullManager for task args)."""
        self.enqueue_local_batch([task_id])

    def enqueue_local_batch(self, task_ids: list[TaskID]) -> None:
        """Batched placement hand-off: a beat's whole lease group for
        this node lands with one record-lookup pass and ONE queue
        critical section instead of a per-task boundary crossing (the
        fused schedule->lease->dispatch path).  Semantics per task are
        exactly ``enqueue_local``'s, drain-race bounce included."""
        if self._draining:
            # route_local raced the drain: back to global scheduling
            for task_id in task_ids:
                self._enqueue(task_id)
            return
        recs = self.task_manager.get_many(task_ids)
        pulls_by_task: dict[TaskID, list] = {}
        from .object_store import PLASMA_KINDS
        for task_id, rec in zip(task_ids, recs):
            if rec is None:
                continue
            pulls = []
            for a in rec.spec.args:
                if isinstance(a, ObjectRef):
                    kind, size = self.store.plasma_info(a.id)
                    if kind in PLASMA_KINDS and \
                            not self.cluster.directory.has_location(
                                a.id, self.row):
                        pulls.append((a.id, size))
            if pulls:
                pulls_by_task[task_id] = pulls
        with self._cv:
            now = _clk.monotonic()
            for task_id, rec in zip(task_ids, recs):
                if rec is not None:
                    self._planned_add(rec.spec.resources, 1)
                if task_id in pulls_by_task:
                    self._pull_pending[task_id] = len(
                        pulls_by_task[task_id])
                self._local_queue.append(
                    task_id,
                    rec.spec.resources.key() if rec is not None else None)
                self._local_since[task_id] = now
            self._dirty = True
            self._cv.notify_all()
        if self._draining:
            # a placement round snapshotted before the drain mask landed
            # routed here: bounce straight back to global scheduling so
            # the guarantee "zero new leases after drain_node" holds
            with self._cv:
                for task_id, rec in zip(task_ids, recs):
                    if task_id in self._local_queue:
                        self._local_queue.remove(task_id)
                        self._local_since.pop(task_id, None)
                        self._pull_pending.pop(task_id, None)
                        if rec is not None:
                            self._planned_add(rec.spec.resources, -1)
                        self._queue.append(task_id)
                self._cv.notify_all()
        if pulls_by_task:
            from .pull_manager import PullPriority
            for task_id, pulls in pulls_by_task.items():
                for oid, size in pulls:
                    self.cluster.pull_manager.request_pull(
                        oid, size, self.row, PullPriority.TASK_ARG,
                        callback=lambda _ok, t=task_id: self._pull_done(t))

    def _pull_done(self, task_id: TaskID) -> None:
        with self._cv:
            left = self._pull_pending.get(task_id)
            if left is None:
                return
            if left <= 1:
                del self._pull_pending[task_id]
                self._dirty = True
                self._cv.notify_all()
            else:
                self._pull_pending[task_id] = left - 1

    def _planned_add(self, resources, sign: int) -> None:
        # caller holds _cv
        vec = resources.dense(self.crm.resource_index,
                              self.crm.avail.shape[1])
        if self._planned_cu is None or \
                self._planned_cu.shape[0] < vec.shape[0]:
            import numpy as _np
            new = _np.zeros(vec.shape[0], dtype=_np.int64)
            if self._planned_cu is not None:
                new[:self._planned_cu.shape[0]] = self._planned_cu
            self._planned_cu = new
        if sign > 0:
            self._planned_cu[:vec.shape[0]] += vec
        else:
            self._planned_cu[:vec.shape[0]] -= vec

    def planned_snapshot(self):
        with self._cv:
            return None if self._planned_cu is None \
                else self._planned_cu.copy()

    def _dep_ready(self, task_id: TaskID) -> None:
        fallback = None
        with self._cv:
            left = self._waiting.get(task_id)
            if left is None:
                return
            if left <= 1:
                del self._waiting[task_id]
                if self._stopped:
                    # node was removed while this task awaited deps: hand
                    # it to the surviving raylet recorded at drain time
                    fallback = getattr(self, "_removal_fallback", None)
                else:
                    self._queue.append(task_id)
                    self._dirty = True
                    self._cv.notify_all()
            else:
                self._waiting[task_id] = left - 1
        if fallback is not None:
            fallback.enqueue_forwarded(task_id)

    def _enqueue(self, task_id: TaskID) -> None:
        with self._cv:
            self._queue.append(task_id)
            self._dirty = True
            self._cv.notify_all()

    def _notify_dirty(self) -> None:
        with self._cv:
            self._dirty = True
            self._cv.notify_all()

    # -- scheduling loop ----------------------------------------------------
    def _loop(self) -> None:
        """Event-driven: wakes only when the dirty flag was raised (task
        arrival, dep readiness, worker idle, resources freed) — a leftover
        queue alone does NOT re-trigger, so an unplaceable backlog parks
        instead of busy-spinning."""
        while True:
            with self._cv:
                while True:
                    # liveness pong: every wake (including health pings via
                    # _notify_dirty) re-stamps — a wedged batch or a dead
                    # thread stops the stamps and the health manager sees it
                    self._last_pong = _clk.monotonic()
                    if self._stopped or (self._dirty and
                                         (self._queue or self._local_queue)):
                        break
                    # while pipelined-lease entries exist, wake on a
                    # timer too: a commit that raced a worker-state
                    # change has no other wake-up to recall it
                    if self._assigned_total > 0:
                        if not self._cv.wait(0.2):
                            break       # timed out: reconcile below
                    else:
                        self._cv.wait()
                if self._stopped:
                    return
                self._dirty = False
                batch = list(self._queue)
                self._queue.clear()
            round_t0 = _clk.monotonic()
            try:
                self._reconcile_assigned()
                # the timed wake must ALSO run the stale-lease recall:
                # a task committed behind a long-running (never-blocking)
                # holder has no other wake-up to pull it back
                with self._cv:
                    any_assigned = self._assigned_total > 0
                if any_assigned:
                    self._spill_stale_leases()
                if batch:
                    leftover = self._place_batch(batch)
                    if leftover:
                        with self._cv:
                            # infeasible-now tasks park at the front, in order
                            self._queue.extendleft(reversed(leftover))
                        # infeasible backlog is autoscaler demand: wake it
                        asc = getattr(self.cluster, "autoscaler", None)
                        if asc is not None:
                            asc.kick()
                self._drain_local()
                if batch:
                    self._round_durations.append(
                        _clk.monotonic() - round_t0)
            except Exception:   # noqa: BLE001 — one bad batch must not
                # kill the node's scheduling thread (every later task
                # would hang); the batch's tasks are lost to this round
                # but retriable ones re-enter via their owners
                import traceback
                traceback.print_exc()

    # -- batch scheduling ---------------------------------------------------
    def _schedule_rows(self, batch: list) -> list[int]:
        """Choose a node row for every task record in the batch.

        Two-pass suspect avoidance: the first pass runs with SUSPECT
        rows (gray failures flagged by the health manager) soft-masked
        out of the snapshot; any task that pass could not place retries
        against the full cluster — a degraded node beats parking
        feasible work, but only as a last resort.
        """
        rows = self._schedule_rows_soft(batch)
        if self._suspect_softmask and any(r < 0 for r in rows):
            snapshot = self._effective_snapshot(avoid_suspect=False)
            n_rows = snapshot.node_mask.shape[0]
            for t, r in enumerate(rows):
                if r >= 0:
                    continue
                spec = batch[t].spec
                req = spec.resources.dense(self.crm.resource_index,
                                           snapshot.totals.shape[1])
                rows[t] = self._policy.schedule(
                    snapshot, req, self._options_for(spec, n_rows))
        return rows

    def _schedule_rows_soft(self, batch: list) -> list[int]:
        """First placement pass (suspect rows soft-masked).

        Returns one row per record (-1 = infeasible/park).  Uses the device
        water-fill kernel for large uniform batches, the CPU policy
        otherwise — bit-identical placements either way (parity contract).
        """
        cfg = get_config()
        specs = [rec.spec for rec in batch]
        # ALL-DEFAULT batches take the subgroup route (device or its
        # host twin): locality bias, lease-avoidance, and top-k are
        # device surfaces too (ops/locality_kernel.py).  Both backends
        # process subgroups keyed (class, locality row, avoid) in
        # first-appearance order, so scheduler_device_batch_min is not
        # observable in placements (top-k rounds excepted — documented
        # sampling divergence)
        if (cfg.scheduler_device_backend
                and all(s.strategy.kind is SchedulingStrategyKind.DEFAULT
                        for s in specs)):
            prefs = [self._locality_row(s) if not
                     (s.task_id in self._avoid_local) else None
                     for s in specs]
            avoids = [s.task_id in self._avoid_local for s in specs]
            with self._cv:      # flags consumed, like the host path
                for s, a in zip(specs, avoids):
                    if a:
                        self._avoid_local.discard(s.task_id)
            n_sub = len({(s.scheduling_class(), p, a) for s, p, a
                         in zip(specs, prefs, avoids)})
            if (len(batch) >= cfg.scheduler_device_batch_min
                    and n_sub <= cfg.tpu_group_capacity):
                return self._schedule_rows_device(specs, prefs, avoids)
            return self._schedule_rows_host_subgrouped(specs, prefs,
                                                       avoids)
        # mixed-strategy batches: per-task CPU policy on a snapshot
        # (sequential within the round), partitioned by scheduling class
        # in first-appearance order
        snapshot = self._effective_snapshot()
        by_class: dict[tuple, list[int]] = {}
        for t, spec in enumerate(specs):
            by_class.setdefault(spec.scheduling_class(), []).append(t)
        rows = [-1] * len(specs)
        for idxs in by_class.values():
            # one dense vector per class (identical by definition), as the
            # device path does
            req = specs[idxs[0]].resources.dense(
                self.crm.resource_index, snapshot.totals.shape[1])
            for t in idxs:
                rows[t] = self._policy.schedule(
                    snapshot, req,
                    self._options_for(specs[t],
                                      snapshot.node_mask.shape[0]))
        return rows

    def _schedule_rows_host_subgrouped(self, specs, prefs,
                                       avoids) -> list[int]:
        """Host twin of the device subgroup path: the SAME
        (class, pref, avoid) subgroups in first-appearance order — so
        small rounds and device rounds evolve ``avail`` identically
        (the batch-size threshold stays unobservable) and the locality
        probe is never run twice.

        Multi-task subgroups place through the pure-numpy water-fill
        (``ops.hybrid_kernel.schedule_group_host`` — one vectorized
        call per subgroup, bit-identical to the sequential policy by
        the parity contract) instead of per-task ``compute_keys``
        loops: the per-task path's numpy overhead on the scheduling
        thread was the dominant GIL cost of tiny-task rounds.
        Singletons and top-k sampling rounds keep the per-task policy
        (the host sampler draws per task)."""
        snapshot = self._effective_snapshot()
        n_rows = snapshot.node_mask.shape[0]
        by_sub: dict[tuple, list[int]] = {}
        for t, spec in enumerate(specs):
            key = (spec.scheduling_class(),
                   prefs[t] if prefs[t] is not None else -1, avoids[t])
            by_sub.setdefault(key, []).append(t)
        rows = [-1] * len(specs)
        vec_ok = get_config().scheduler_top_k_fraction == 0
        if vec_ok:
            from ..ops.hybrid_kernel import schedule_group_host
            from ..scheduling.contract import threshold_fp
            thr = threshold_fp(None)
        for (cls_key, pref, avoid), idxs in by_sub.items():
            req = specs[idxs[0]].resources.dense(
                self.crm.resource_index, snapshot.totals.shape[1])
            if vec_ok and len(idxs) > 1:
                gmask = None
                if avoid and 0 <= self.row < n_rows:
                    gmask = np.ones(n_rows, dtype=bool)
                    gmask[self.row] = False
                # avoid wins over pref, matching the per-task branch
                # below and _schedule_rows' construction (avoid tasks
                # get pref None there — the local data node is exactly
                # what starved them)
                counts_row, new_avail = schedule_group_host(
                    snapshot.avail, snapshot.totals, snapshot.node_mask,
                    req, len(idxs), gmask, thr,
                    pref_row=-1 if avoid else int(pref))
                snapshot.avail[:] = new_avail       # sequential carry
                slots = np.repeat(
                    np.concatenate([np.arange(n_rows, dtype=np.int32),
                                    np.array([-1], dtype=np.int32)]),
                    counts_row)
                for t, r in zip(idxs, slots):
                    rows[t] = int(r)
                continue
            for t in idxs:
                if avoid:
                    opts = SchedulingOptions(avoid_local_node=True,
                                             local_node_row=self.row)
                elif pref >= 0:
                    opts = SchedulingOptions(
                        scheduling_type=SchedulingType.NODE_AFFINITY,
                        node_row=int(pref), soft=True)
                else:
                    opts = SchedulingOptions()
                rows[t] = self._policy.schedule(snapshot, req, opts)
        return rows

    def _schedule_rows_device(self, specs: list,
                              prefs: list | None = None,
                              avoids: list | None = None) -> list[int]:
        """One device water-fill call places the whole batch (north star).

        Subgroups key on (scheduling class, locality row, avoid flag):
        locality-biased groups pre-place on their preferred row (soft
        affinity, bit-identical to the host sequence), avoid groups mask
        out this node, and with ``scheduler_top_k_fraction`` > 0 the
        no-preference groups spread over their top-k keys on device
        (documented sampling divergence — ops/locality_kernel.py)."""
        import jax.numpy as jnp

        from ..ops import schedule_grouped
        from ..scheduling.contract import threshold_fp

        if prefs is None:
            prefs = [None] * len(specs)
        if avoids is None:
            avoids = [False] * len(specs)
        cfg = get_config()
        width = self.crm.arrays()[0].shape[1]
        groups: dict[tuple, int] = {}
        reqs: list[np.ndarray] = []
        counts: list[int] = []
        pref_rows: list[int] = []
        avoid_flags: list[bool] = []
        task_group = np.empty(len(specs), dtype=np.int32)
        for t, spec in enumerate(specs):
            pref = prefs[t] if prefs[t] is not None else -1
            key = (spec.scheduling_class(), pref, avoids[t])
            g = groups.get(key)
            if g is None:
                g = len(reqs)
                groups[key] = g
                reqs.append(spec.resources.dense(self.crm.resource_index,
                                                 width))
                counts.append(0)
                pref_rows.append(int(pref))
                avoid_flags.append(bool(avoids[t]))
            counts[g] += 1
            task_group[t] = g
        G = len(reqs)
        # pad the class axis to a power-of-2 bucket: every distinct G would
        # otherwise be a fresh XLA compilation (SURVEY §7 hard part 3);
        # count-0 padding rows are no-ops in the water-fill
        Gp = max(8, 1 << (G - 1).bit_length())
        req_arr = np.zeros((Gp, width), dtype=np.int32)
        req_arr[:G] = np.stack(reqs)
        cnt_arr = np.zeros(Gp, dtype=np.int32)
        cnt_arr[:G] = counts
        pref_arr = np.full(Gp, -1, dtype=np.int32)
        pref_arr[:G] = pref_rows
        top_k = cfg.scheduler_top_k_fraction
        plain = (pref_arr < 0).all() and not any(avoid_flags)
        if plain and top_k == 0 and not cfg.scheduler_sharded_state \
                and cfg.scheduler_delta_beats:
            # incremental heartbeat: no snapshot copy, no full upload —
            # the resident mirror syncs from the CRM dirty journal and
            # planned load rides along as per-beat avail overrides
            counts_host = self._schedule_rows_delta(req_arr[:G],
                                                    cnt_arr[:G])
            N = counts_host.shape[1] - 1
        else:
            snapshot = self._effective_snapshot()
            totals, avail, mask = (snapshot.totals, snapshot.avail,
                                   snapshot.node_mask)
            if totals.shape[1] != width:
                # a resource column appeared between the width probe and
                # the snapshot; dense vectors only append columns, so
                # zero-padding the request rows is exact
                wider = np.zeros((Gp, totals.shape[1]), dtype=np.int32)
                wider[:, :width] = req_arr
                req_arr = wider
            N = totals.shape[0]
            gmask = np.ones((Gp, N), dtype=bool)
            for g, av in enumerate(avoid_flags):
                if av and 0 <= self.row < N:
                    gmask[g, self.row] = False
            if cfg.scheduler_sharded_state and plain and top_k == 0:
                # host gmask: the sharded branch pads its node axis
                counts_host = self._schedule_sharded(
                    totals, avail, mask, req_arr, cnt_arr, gmask)[:G]
            elif top_k > 0:
                counts_host = self._schedule_device_topk(
                    totals, avail, mask, req_arr, cnt_arr, gmask,
                    pref_arr, cfg)[:G]
            elif plain:
                counts_dev, _ = schedule_grouped(
                    jnp.asarray(totals), jnp.asarray(avail),
                    jnp.asarray(mask), jnp.asarray(req_arr),
                    jnp.asarray(cnt_arr), jnp.asarray(gmask),
                    jnp.int32(threshold_fp(None)))
                counts_host = np.asarray(counts_dev)[:G]
            else:
                from ..ops.locality_kernel import schedule_grouped_localized
                counts_dev, _ = schedule_grouped_localized(
                    jnp.asarray(totals), jnp.asarray(avail),
                    jnp.asarray(mask), jnp.asarray(req_arr),
                    jnp.asarray(cnt_arr), jnp.asarray(gmask),
                    jnp.asarray(pref_arr), jnp.int32(threshold_fp(None)))
                counts_host = np.asarray(counts_dev)[:G]
        # expand (G, N+1) counts into per-task rows, class-internal order
        # node-row-ascending (tasks within a class are interchangeable)
        slots = [np.repeat(
            np.concatenate([np.arange(N, dtype=np.int32),
                            np.array([-1], dtype=np.int32)]),
            counts_host[g]) for g in range(G)]
        cursor = np.zeros(G, dtype=np.int64)
        rows = []
        for t in range(len(specs)):
            g = task_group[t]
            rows.append(int(slots[g][cursor[g]]))
            cursor[g] += 1
        return rows

    def _schedule_rows_delta(self, req_arr, cnt_arr) -> "np.ndarray":
        """The fused delta-heartbeat placement call
        (scheduling.policy.DeltaScheduler): the device mirror syncs
        incrementally from the CRM's dirty journal; this node's view of
        planned-but-undispatched load rides along as per-beat avail
        overrides and suspect rows as a per-beat soft mask — both with
        the exact ``_effective_snapshot`` arithmetic, so placements are
        bit-identical to the snapshot path.  One counts readback per
        beat.  Returns (G, N+1) int32 counts.

        The engine comes from ``make_delta_scheduler``: with
        ``scheduler_shards`` resolving past one chip the mirror and the
        beat shard over the device mesh (ShardedDeltaScheduler), else
        the single-device DeltaScheduler — placements are bit-identical
        either way."""
        from ..scheduling.sharded_delta import make_delta_scheduler
        eng = self._delta_engine
        if eng is None:
            eng = self._delta_engine = make_delta_scheduler(self.crm)
        _v, totals_f, avail_f, place_mask, _rows = self.crm.delta_view(-2)
        # suspect soft-avoid, same healthy-survivor rule as
        # _effective_snapshot (suspect is advisory, never hard)
        self._suspect_softmask = False
        extra = None
        sus = self.crm.suspect_mask()
        if sus.any():
            n = min(sus.shape[0], place_mask.shape[0])
            healthy = place_mask.copy()
            healthy[:n] &= ~sus[:n]
            if healthy.any():
                extra = ~sus
                self._suspect_softmask = True
        overrides: dict[int, np.ndarray] = {}
        for row, planned in self._planned_overrides(
                avail_f.shape[1]).items():
            if not 0 <= row < avail_f.shape[0]:
                continue
            w = min(avail_f.shape[1], planned.shape[0])
            base = avail_f[row].astype(np.int64)
            base[:w] = (base[:w] - planned[:w]).clip(-(2**30), 2**30)
            overrides[row] = base.astype(np.int32)
        counts = eng.beat(req_arr, cnt_arr, overrides=overrides,
                          extra_mask=extra)
        cfg = get_config()
        if cfg.lease_plane_enabled and cfg.lease_budget_source == "beat":
            self._publish_beat_budgets(eng)
        return counts

    def _publish_beat_budgets(self, eng) -> None:
        """Hand the beat's device-priced (class x node) lease budgets —
        already host-side, they rode the beat's single readback — to
        the process-wide budget board the head's ``AgentHub`` sizes
        grants from (the closed dispatch loop: beat -> readback ->
        grantor -> raylet lease cache).  Budget rows are re-keyed from
        interned request vectors to the lease plane's class-key strings
        (``node_agent._lease_class_key`` format)."""
        from ..leasing.board import budget_board
        budgets = eng.last_budgets()
        if budgets is None:
            return
        index = self.crm.resource_index
        rows: dict[str, np.ndarray] = {}
        for slot, vec in eng.class_vectors().items():
            if slot >= budgets.shape[0]:
                continue        # interned after the beat; next beat has it
            parts = sorted((index.name(int(c)), int(vec[c]))
                           for c in np.flatnonzero(vec))
            ck = ",".join(f"{k}:{v}" for k, v in parts) or "zero"
            rows[ck] = budgets[slot]
        budget_board().publish(eng.budget_seq, rows)

    def _schedule_device_topk(self, totals, avail, mask, req_arr,
                              cnt_arr, gmask, pref_arr,
                              cfg) -> "np.ndarray":
        """Top-k rounds on device: locality groups pre-place via the
        localized kernel (affinity is deterministic, no sampling), then
        the remaining groups spread over their top-k keys with a pinned
        (row, round) random stream — deterministic replay, documented
        divergence from the host sampler's per-task draws."""
        from ..ops.locality_kernel import (schedule_grouped_localized_np,
                                          schedule_grouped_topk_np)
        self._topk_round = getattr(self, "_topk_round", 0) + 1
        has_pref = pref_arr >= 0
        counts_out = np.zeros((req_arr.shape[0], totals.shape[0] + 1),
                              dtype=np.int32)
        avail_now = avail
        if has_pref.any():
            loc_cnt = np.where(has_pref, cnt_arr, 0).astype(np.int32)
            c_loc, avail_now = schedule_grouped_localized_np(
                totals, avail_now, mask, req_arr, loc_cnt, pref_arr,
                group_masks=gmask, spread_threshold=None)
            counts_out += c_loc
        topk_cnt = np.where(has_pref, 0, cnt_arr).astype(np.int32)
        if topk_cnt.any():
            c_topk, _ = schedule_grouped_topk_np(
                totals, avail_now, mask, req_arr, topk_cnt,
                seed=self.row, round_index=self._topk_round,
                group_masks=gmask,
                k_abs=cfg.scheduler_top_k_absolute,
                k_frac=cfg.scheduler_top_k_fraction)
            counts_out += c_topk
        return counts_out

    def _schedule_sharded(self, totals, avail, mask, req_arr, cnt_arr,
                          gmask) -> "np.ndarray":
        """The device placement call with cluster-state rows SHARDED
        over all local devices (the live-path form of the multi-chip
        layout ``__graft_entry__.dryrun_multichip`` proves): each device
        owns N/n_dev node rows; the water-fill's global sums lower to
        all-reduces over ICI.  Node rows pad to a mesh multiple with
        mask-False rows (no-ops in the kernel).  Returns host counts of
        shape (Gp, N+1) — real node columns plus the infeasible
        column."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..ops import schedule_grouped
        from ..scheduling.contract import threshold_fp
        # local_devices, NOT devices(): in multi-process JAX the global
        # list includes non-addressable chips, and device_put of host
        # arrays onto those raises
        devs = jax.local_devices()
        n_dev = len(devs)
        n = totals.shape[0]
        pad = (-n) % n_dev
        if pad:
            totals = np.pad(totals, ((0, pad), (0, 0)))
            avail = np.pad(avail, ((0, pad), (0, 0)))
            mask = np.pad(mask, (0, pad))               # padding: dead rows
            gmask = np.pad(gmask, ((0, 0), (0, pad)))
        cache = getattr(self, "_shard_cache", None)
        if cache is None or cache[0] != n_dev:
            mesh = Mesh(np.array(devs), ("nodes",))
            shardings = {
                "rows": NamedSharding(mesh, P("nodes", None)),
                "vec": NamedSharding(mesh, P("nodes")),
                "repl": NamedSharding(mesh, P()),
                "gn": NamedSharding(mesh, P(None, "nodes")),
            }
            step = jax.jit(
                schedule_grouped,
                out_shardings=(shardings["repl"], shardings["rows"]))
            self._shard_cache = (n_dev, shardings, step)
        _, sh, step = self._shard_cache
        # device_put takes host numpy + sharding directly: ONE sharded
        # transfer per array (a jnp.asarray first would materialize on
        # the default device and reshard — double transfer)
        counts_dev, _ = step(
            jax.device_put(totals, sh["rows"]),
            jax.device_put(avail, sh["rows"]),
            jax.device_put(mask, sh["vec"]),
            jax.device_put(req_arr, sh["repl"]),
            jax.device_put(cnt_arr, sh["repl"]),
            jax.device_put(gmask, sh["gn"]),
            jnp.int32(threshold_fp(None)))
        counts = np.asarray(counts_dev)
        if pad:
            # drop padding-node columns; the infeasible column is last
            counts = np.concatenate([counts[:, :n], counts[:, -1:]],
                                    axis=1)
        return counts

    def _effective_snapshot(self, avoid_suspect: bool = True):
        """CRM snapshot minus every node's planned-but-undispatched load
        AND its agent-locally-running load (tasks an autonomous agent
        leased without the head — reported on the batched agent_sync),
        so placement rounds do not over-assign nodes whose queues or
        local leases are already deep.

        With ``avoid_suspect`` (the default), SUSPECT rows are masked
        out too — but only while at least one healthy node survives,
        and ``self._suspect_softmask`` records that the mask was
        applied so ``_schedule_rows`` knows a -1 merits a full-cluster
        retry (suspect is advisory, never a hard exclusion)."""
        snapshot = self.crm.snapshot()
        self._suspect_softmask = False
        if avoid_suspect:
            sus = self.crm.suspect_mask()
            n = min(sus.shape[0], snapshot.node_mask.shape[0])
            if sus[:n].any():
                healthy = snapshot.node_mask.copy()
                healthy[:n] &= ~sus[:n]
                if healthy.any():
                    snapshot.node_mask = healthy
                    self._suspect_softmask = True
        for row, planned in self._planned_overrides(
                snapshot.avail.shape[1]).items():
            w = min(snapshot.avail.shape[1], planned.shape[0])
            snapshot.avail[row, :w] = (
                snapshot.avail[row, :w].astype(np.int64) - planned[:w]
            ).clip(-(2**30), 2**30).astype(np.int32)
        return snapshot

    def _planned_overrides(self, width: int) -> dict[int, np.ndarray]:
        """Per-row planned-but-undispatched + agent-locally-running
        debits (int64 cu vectors): the ephemeral load every placement
        round subtracts — applied to the snapshot copy by
        ``_effective_snapshot`` and as per-beat device overrides by
        ``_schedule_rows_delta`` (identical arithmetic either way)."""
        out: dict[int, np.ndarray] = {}
        for row, raylet in list(self.cluster.raylets.items()):
            planned = raylet.planned_snapshot()
            local = raylet.agent_local_cu
            if local:
                vec = ResourceRequest.from_cu_dict(local).dense(
                    self.crm.resource_index, width).astype(np.int64)
                if planned is None:
                    planned = vec
                else:
                    n = max(planned.shape[0], vec.shape[0])
                    merged = np.zeros(n, dtype=np.int64)
                    merged[:planned.shape[0]] += planned
                    merged[:vec.shape[0]] += vec
                    planned = merged
            if planned is not None:
                out[row] = planned
        return out

    def _locality_row(self, spec) -> int | None:
        """Node row holding the most bytes of the spec's plasma args, or
        None when locality gives no signal (no plasma args, or the knob
        is off).  Reference: the core worker's locality-aware lease
        policy targets the raylet with the most object bytes local."""
        if not spec.args or not get_config().locality_aware_scheduling:
            return None
        by_row: dict[int, int] = {}
        from .object_store import PLASMA_KINDS
        for a in spec.args:
            if isinstance(a, ObjectRef):
                kind, size = self.store.plasma_info(a.id)
                if kind in PLASMA_KINDS:
                    for r in self.cluster.directory.locations(a.id):
                        by_row[r] = by_row.get(r, 0) + size
        if not by_row:
            return None
        # max bytes, lowest row on ties (deterministic)
        return min(by_row, key=lambda r: (-by_row[r], r))

    def _options_for(self, spec, n_rows: int) -> SchedulingOptions:
        kind = spec.strategy.kind
        if kind is SchedulingStrategyKind.DEFAULT:
            if spec.task_id in self._avoid_local:
                # lease-timeout spillback: one placement that excludes
                # this node (flag consumed; locality skipped — the local
                # data node is exactly what starved the task)
                self._avoid_local.discard(spec.task_id)
                return SchedulingOptions(avoid_local_node=True,
                                         local_node_row=self.row)
            row = self._locality_row(spec)
            if row is not None:
                # soft affinity: land on the max-local-bytes node when it
                # can take the task, hybrid otherwise
                return SchedulingOptions(
                    scheduling_type=SchedulingType.NODE_AFFINITY,
                    node_row=row, soft=True)
            return SchedulingOptions()
        if kind is SchedulingStrategyKind.SPREAD:
            return SchedulingOptions(scheduling_type=SchedulingType.SPREAD)
        if kind is SchedulingStrategyKind.NODE_AFFINITY:
            row = self.crm.row_of(spec.strategy.node_id)
            return SchedulingOptions(
                scheduling_type=SchedulingType.NODE_AFFINITY,
                node_row=row if row is not None else -1,
                soft=spec.strategy.soft)
        if kind is SchedulingStrategyKind.NODE_LABEL:
            # resolve the selector into a node mask against live labels
            mask = self.crm.label_mask(dict(spec.strategy.label_selector))
            return SchedulingOptions(
                scheduling_type=SchedulingType.NODE_LABEL,
                node_mask=mask[:n_rows], soft=spec.strategy.soft)
        if kind is SchedulingStrategyKind.PLACEMENT_GROUP:
            # pin to the group's reserved bundles; a still-pending group
            # parks the task (all-False mask) until the PG manager's
            # commit wakes the raylets (SURVEY §3.5).  "dead" groups are
            # failed earlier in _place_batch; park defensively if one
            # races through here.
            verdict, options = self.cluster.pg_manager.\
                scheduling_options_for(spec.strategy, n_rows)
            if verdict == "dead":
                return SchedulingOptions(
                    node_mask=np.zeros(n_rows, dtype=bool))
            return options
        return SchedulingOptions()

    def _place_batch(self, batch: list[TaskID]) -> list[TaskID]:
        """Assign every task a node (ONE scheduling decision per task);
        returns the infeasible leftover."""
        recs = []
        for task_id in batch:
            rec = self.task_manager.get(task_id)
            if rec is None or rec.done:
                continue
            strat = rec.spec.strategy
            if strat.kind is SchedulingStrategyKind.PLACEMENT_GROUP:
                verdict, _ = self.cluster.pg_manager.\
                    scheduling_options_for(strat, 0)
                if verdict == "dead":
                    # removed/unknown group or bad bundle index: fail the
                    # task (reference: tasks of a removed PG error out)
                    self._fail_unscheduled(
                        rec, "placement group removed, unknown, or "
                        "bundle index out of range")
                    continue
            if strat.kind is SchedulingStrategyKind.NODE_AFFINITY \
                    and not strat.soft \
                    and self.crm.row_of(strat.node_id) is None:
                # hard affinity to a node that no longer exists can
                # NEVER place — fail fast instead of parking forever
                # (reference: hard NodeAffinity to a dead node fails
                # the task as unschedulable)
                self._fail_unscheduled(
                    rec, "hard node affinity to a dead or unknown "
                    f"node {strat.node_id.hex()[:12]}")
                continue
            recs.append(rec)
        if not recs:
            return []
        rows = self._schedule_rows(recs)
        leftover: list[TaskID] = []
        local_ids: list[TaskID] = []
        remote: dict[int, list[TaskID]] = {}
        for rec, row in zip(recs, rows):
            if row < 0:
                leftover.append(rec.spec.task_id)
            elif row == self.row:
                local_ids.append(rec.spec.task_id)
            else:
                remote.setdefault(row, []).append(rec.spec.task_id)
        # fused hand-off: the beat's placement readback becomes per-node
        # lease groups delivered in one call per target raylet
        if local_ids:
            self.enqueue_local_batch(local_ids)
        for row, ids in remote.items():
            if not self.cluster.route_local_batch(row, ids):
                leftover.extend(ids)            # target died: retry
        return leftover

    def _drain_local(self) -> None:
        """Dispatch placed tasks to workers; stops scanning after a run of
        consecutive failures (worker/resource-starved queue parks until the
        next idle/free event).  The queue is bucketed by scheduling-class
        key (the reference ClusterTaskManager's SchedulingClass-keyed
        dispatch queues): a class whose resource demand cannot fit skips
        the rest of its bucket, so a deep single-class backlog costs at
        most one chunk copy per pass instead of a full queue scan;
        buckets are visited oldest-head first for cross-class
        fairness."""
        from itertools import islice
        max_misses = 8
        chunk_size = 128
        misses = 0
        env_missed: set = set()         # env keys already counted a miss
        kicked = False                  # autoscaler kicked this pass
        with self._cv:
            if self._draining or not self._local_queue:
                return
            # oldest class first (head-entry enqueue time): bucket order
            # must not starve a lone task of a late class behind an
            # earlier class's steady stream — the fairness the flat FIFO
            # gave, at class granularity
            class_keys = sorted(
                self._local_queue.classes(),
                key=lambda k: min(
                    (self._local_since.get(t, float("inf"))
                     for t in islice(self._local_queue.bucket(k), 1)),
                    default=float("inf")))
        for key in class_keys:
            # buckets snapshot in CHUNKS: a class that cannot fit stops
            # after one chunk, so a 100k-deep starved backlog costs a
            # bounded copy per pass, not O(queue)
            skipped = 0         # examined but left queued this pass
            class_full = False
            while not class_full:
                if misses >= max_misses:
                    return
                with self._cv:
                    chunk = list(islice(self._local_queue.bucket(key),
                                        skipped, skipped + chunk_size))
                    # pull state snapshotted WITH the chunk: enqueue sets
                    # _pull_pending in the same _cv section as the queue
                    # append, so a task enqueued mid-pass with in-flight
                    # pulls cannot appear in a chunk without its entry
                    # (intersect with the chunk — O(chunk), not O(pending))
                    pull_pending = {t for t in chunk
                                    if t in self._pull_pending}
                if not chunk:
                    break
                for task_id in chunk:
                    if misses >= max_misses:
                        return
                    if task_id in pull_pending:
                        skipped += 1    # args still in flight this pass
                        continue
                    rec = self.task_manager.get(task_id)
                    if rec is None or rec.done:
                        with self._cv:
                            try:
                                self._local_queue.remove(task_id)
                            except ValueError:
                                continue    # concurrent cancel removed it
                            self._local_since.pop(task_id, None)
                            self._env_miss_since.pop(task_id, None)
                            if rec is not None:
                                self._planned_add(rec.spec.resources, -1)
                        continue
                    spec = rec.spec
                    # reserve resources BEFORE popping a worker
                    # (pool.release fires the idle wake-up, so a
                    # speculative pop-then-release would spin the loop)
                    if not self.crm.subtract(self.row, spec.resources):
                        if not kicked:
                            # resource-starved backlog = autoscaler demand
                            asc = getattr(self.cluster, "autoscaler", None)
                            if asc is not None:
                                asc.kick()
                            kicked = True
                        misses += 1
                        class_full = True
                        break           # rest of the bucket cannot fit
                    outcome = self._drain_try_worker(task_id, rec, spec,
                                                     env_missed)
                    if outcome == "stop":
                        return
                    if outcome == "ok":
                        continue        # entry removed from the bucket
                    if outcome == "miss":
                        misses += 1
                    skipped += 1        # miss/skip leave the entry queued
                    # (over-counts when a helper removed the entry — a
                    # later pass re-examines anything this one missed)
        return

    def _drain_try_worker(self, task_id, rec, spec,
                          env_missed: set) -> str:
        """Second half of one drain step: lease a worker (env-keyed or
        default, else a pipelined commit) and dispatch.  Outcomes:
        ``"ok"`` dispatched/committed, ``"miss"`` count against the miss
        budget, ``"skip"`` no budget charge (env miss already counted,
        concurrent removal), ``"stop"`` end the whole pass
        (worker-limited)."""
        if spec.runtime_env:
            worker, env_k = self._pop_env_worker(task_id, rec, spec)
            if worker is None:
                # one miss per env KEY per scan (like a full class's
                # single miss for resources): a block of same-env tasks
                # parked at a barrier must not eat the whole miss budget
                # and starve runnable default tasks behind them
                if env_k is None or env_k not in env_missed:
                    if env_k is not None:
                        env_missed.add(env_k)
                    return "miss"   # waits for its env worker (or
                #                     failed staging); others may still
                return "skip"       # dispatch
        else:
            worker = self.pool.pop_idle()
            if worker is None:
                # pipelined lease: commit the task to a BUSY worker's
                # soft queue (resources stay debited); the exec frame
                # ships the instant that worker's current result
                # lands, cutting the result->rescan->dispatch round
                # trip out of the tiny-task critical path
                depth = get_config().worker_pipeline_depth
                target = self.pool.pipeline_target(None, depth) \
                    if depth > 1 else None
                if target is not None:
                    committed = False
                    with self._cv:
                        # re-validate AT COMMIT: the target may have
                        # died/blocked/been released since selection
                        # (the reconcile sweep covers what still
                        # slips through this non-atomic check)
                        if not target.dead and not target.blocked \
                                and target.leased_task is not None:
                            try:
                                self._local_queue.remove(task_id)
                            except ValueError:
                                self.crm.add_back(self.row,
                                                  spec.resources)
                                return "skip"
                            self._local_since.pop(task_id, None)
                            self._env_miss_since.pop(task_id, None)
                            self._planned_add(spec.resources, -1)
                            target.assigned.append(
                                (task_id, _clk.monotonic()))
                            self._assigned_total += 1
                            committed = True
                    if committed:
                        return "ok"
                    self.crm.add_back(self.row, spec.resources)
                    self._spill_stale_leases()
                    return "stop"
                self.crm.add_back(self.row, spec.resources)
                # worker-limited: park, but tasks that waited past the
                # lease timeout spill back to global placement
                self._spill_stale_leases()
                return "stop"
        with self._cv:
            try:
                self._local_queue.remove(task_id)
            except ValueError:
                self.crm.add_back(self.row, spec.resources)
                self.pool.release(worker)
                return "skip"
            self._local_since.pop(task_id, None)
            self._env_miss_since.pop(task_id, None)
            self._planned_add(spec.resources, -1)
        self._dispatch(worker, rec)
        return "ok"

    def _dispatch(self, worker: WorkerHandle, rec) -> bool:
        spec = rec.spec
        # resolve top-level ObjectRef args (deps are ready by construction)
        # as store descriptors: shm-resident args reach the worker as
        # (offset, size) and are read zero-copy; errors are always in-band.
        # Plane-backed remote nodes ship plasma args BY REFERENCE in the
        # frame's extern table — the agent resolves them against its own
        # arena, so payload bytes never transit the head (reference: task
        # args resolve in the executing node's local plasma store)
        from .object_store import PLASMA_KINDS
        from .worker import ArgRef
        args = []
        extern: list = []       # frame-level descriptors (outside the
        #                         payload pickle, rewritable by the agent)
        pinned: list = []       # shm args stay pinned until task completion
        dep_error = None
        vanished = None
        for a in spec.args:
            if isinstance(a, ObjectRef):
                if self.remote_plane:
                    kind, _size = self.store.plasma_info(a.id)
                    if kind in PLASMA_KINDS:
                        extern.append(("r", a.id.binary()))
                        args.append(ArgRef(("x", len(extern) - 1)))
                        continue
                try:
                    desc = self.store.descriptor_of(a.id)
                except KeyError:
                    # arg vanished between placement and dispatch (lineage
                    # recovery re-seal in flight): park until it reappears
                    vanished = a.id
                    break
                if desc[0] == "s":
                    if self.inline_objects:
                        # remote worker with no plane: copy out of the
                        # arena under the pin, ship bytes, release now
                        desc = ("b", self.store.inline_bytes(a.id, desc))
                    else:
                        pinned.append((a.id, desc[1]))
                if desc[0] == "v" and isinstance(desc[1], RayTaskError):
                    dep_error = desc[1]
                    break
                args.append(ArgRef(desc))
            else:
                args.append(a)
        if vanished is not None:
            self.store.unpin(pinned)
            self.crm.add_back(self.row, spec.resources)
            self.pool.release(worker)
            with self._cv:
                self._waiting[spec.task_id] = 1
            self.store.on_ready(vanished, lambda _oid, t=spec.task_id:
                                self._dep_ready(t))
            return False
        if dep_error is not None:
            # propagate the dependency's error to this task's outputs
            # without executing (reference: failed deps fail the task)
            self.store.unpin(pinned)
            self._finish_with_error(rec, dep_error, worker)
            return False

        fn_id = spec.function_descriptor
        if fn_id not in worker.fn_cache:
            fn_bytes = self._fn_registry.get(fn_id)
            if fn_bytes is None:
                self.store.unpin(pinned)
                self._finish_with_error(rec, RayTaskError(
                    fn_id, "function bytes never reached the driver "
                    "(stub submitted without registration)"), worker)
                return False
            if not worker.send(("fn", fn_id, fn_bytes)):
                self.store.unpin(pinned)
                self._requeue_after_worker_loss(rec, worker)
                return False
            worker.fn_cache.add(fn_id)
        from .object_ref import mark_transferred, transfer_generators
        with transfer_generators() as xfer_gens:
            payload = serialize((tuple(args), spec.kwargs,
                                 spec.num_returns))
        # lineage budget cost, measured here where the args are already
        # serialized (complete() must not re-pickle under the manager lock)
        rec.lineage_bytes = len(payload) + 256
        self._task_start[spec.task_id.binary()] = _clk.now()
        worker.leased_task = spec.task_id.binary()
        worker.leased_streaming = spec.num_returns == -1
        with self._cv:
            self._running[spec.task_id.binary()] = (spec.task_id, worker,
                                                    pinned)
        # plane agents get the task's demand vector appended (7th
        # element, stripped before the worker sees the frame): the
        # agent maintains a local availability view for its autonomous
        # dispatch fast path from exactly these observations
        if self.remote_plane:
            frame = ("exec", spec.task_id.binary(), fn_id, payload,
                     spec.trace_ctx, extern, spec.resources.cu())
        else:
            frame = ("exec", spec.task_id.binary(), fn_id, payload,
                     spec.trace_ctx, extern)
        if not worker.send(frame):
            with self._cv:
                entry = self._running.pop(spec.task_id.binary(), None)
            if entry is not None:
                # a concurrent _on_worker_death that popped first already
                # unpinned, returned resources, and retried/failed the
                # task — doing it again here would double-release
                self.store.unpin(pinned)
                self._requeue_after_worker_loss(rec, worker)
            return False
        mark_transferred(xfer_gens)     # exec frame shipped
        return True

    def _pop_env_worker(self, task_id, rec, spec):
        """Lease a worker matching the task's runtime env, staging the
        env off-thread and spawning a cached env worker on first need
        (reference: the runtime-env agent provisions, then the lease
        retries).  Returns ``(worker_or_None, env_key_or_None)``: a None
        worker means the task cannot dispatch this round (env worker
        busy/booting/staging, or staging failed and the task was sealed
        with RuntimeEnvSetupError); the key lets the scan dedup misses
        per env."""
        from .runtime_env import RuntimeEnvSetupError, env_key
        try:
            key = env_key(spec.runtime_env)
            payload = self.cluster.runtime_env_manager.get_if_ready(key)
        except (RuntimeEnvSetupError, ValueError) as e:
            with self._cv:
                try:
                    self._local_queue.remove(task_id)
                except ValueError:
                    self.crm.add_back(self.row, spec.resources)
                    return None, None
                self._local_since.pop(task_id, None)
                self._env_miss_since.pop(task_id, None)
                self._planned_add(spec.resources, -1)
            self._finish_with_error(rec, RayTaskError(
                spec.function_descriptor, f"runtime_env setup failed: {e}",
                e if isinstance(e, RuntimeEnvSetupError)
                else RuntimeEnvSetupError(str(e))), None)
            return None, None
        if payload is None:
            # unstaged: provision on a side thread — a copytree of a
            # large working_dir on THIS thread would stall every other
            # task's dispatch on the node (reference: the runtime-env
            # agent keeps staging off the raylet's dispatch path)
            self._stage_env_async(key, spec.runtime_env)
            self.crm.add_back(self.row, spec.resources)
            return None, key
        worker = self.pool.pop_idle(key)
        if worker is None:
            # cold start (no worker staged into this env) spawns now;
            # otherwise wait out a grace period first — the busy worker
            # normally returns to idle in microseconds (sequential
            # reuse), but tasks that rendezvous with each other (a
            # barrier under a job-level env) hold their workers, and
            # only growing the cache un-deadlocks them
            now = _clk.monotonic()
            grace = get_config().env_worker_grace_ms / 1000.0
            with self._cv:
                first = self._env_miss_since.setdefault(task_id, now)
            if self.pool.live_env_workers(key) == 0 or now - first > grace:
                with self._cv:
                    # a fresh grace gates the NEXT growth step: without
                    # this re-stamp, every scan after the first lapse
                    # would fork another process
                    self._env_miss_since[task_id] = now
                self.pool.ensure_env_worker(key, payload)
            elif first == now:
                # first miss: nothing else re-triggers the scan if the
                # busy worker never returns, so arm ONE re-check timer
                # per waiting task for just past the grace
                t = threading.Timer(grace * 1.1, self._notify_dirty)
                t.daemon = True
                t.start()
            self.crm.add_back(self.row, spec.resources)
        return worker, key

    def _parent_env_of(self, worker: WorkerHandle) -> dict | None:
        """The runtime env of whatever this worker is executing: its
        leased task's (job-merged) env, or its bound actor's."""
        tid_bin = worker.leased_task
        if tid_bin is not None:
            entry = self._running.get(tid_bin)
            if entry is not None:
                rec = self.task_manager.get(entry[0])
                if rec is not None:
                    return rec.spec.runtime_env
        actor_id = getattr(worker, "actor_binding", None)
        if actor_id is not None and self.actor_manager is not None:
            return self.actor_manager.runtime_env_of(actor_id)
        return None

    def _stage_env_async(self, key: str, env: dict) -> None:
        """Provision a runtime env on a daemon thread, once per key;
        completion (or the now-cached failure) re-wakes the scan."""
        with self._cv:
            if key in self._env_staging:
                return
            self._env_staging.add(key)

        def run() -> None:
            try:
                self.cluster.runtime_env_manager.stage(env)
            except Exception:   # noqa: BLE001 — the manager caches the
                pass            # error; the next scan fails the task
            finally:
                with self._cv:
                    self._env_staging.discard(key)
                self._notify_dirty()
        threading.Thread(target=run, daemon=True,
                         name=f"env-stage-{key[:8]}").start()

    def _spill_stale_leases(self) -> None:
        """Placed tasks that waited longer than ``worker_lease_timeout_ms``
        for a worker re-enter GLOBAL placement (reference: an expired
        worker-lease request is retried and may spill back to another
        raylet).  Tasks with in-flight arg pulls stay (they are making
        progress)."""
        timeout = get_config().worker_lease_timeout_ms / 1000.0
        now = _clk.monotonic()
        moved = []
        multi_node = len(self.cluster.raylets) > 1
        with self._cv:
            if not multi_node:
                pass            # nowhere to spill to; the pipelined-
                # lease recall below still applies single-node
            else:
                self._spill_queue_locked(now, timeout, moved)
        for tid in moved:
            self._enqueue(tid)
        # pipelined-lease staleness: a committed task stuck behind a
        # long-running (but never-blocking) holder past the lease
        # timeout pulls back and re-enters local dispatch
        stale_workers = []
        with self.pool._lock:
            workers = list(self.pool._workers)
        for w in workers:
            with self._cv:
                oldest = w.assigned[0][1] if w.assigned else None
            if oldest is not None and now - oldest > timeout:
                stale_workers.append(w)
        for w in stale_workers:
            # multi-node: spill away from this node like the queue path
            # above; single-node there is nowhere else to go
            self._recall_assigned(w, avoid_local=multi_node)

    def _spill_queue_locked(self, now, timeout, moved) -> None:
        """Move lease-timed-out queue entries into ``moved`` (caller
        holds ``_cv`` and re-enqueues globally)."""
        for tid in list(self._local_queue):
            t0 = self._local_since.get(tid)
            if t0 is None or now - t0 <= timeout or \
                    tid in self._pull_pending:
                continue
            self._local_queue.remove(tid)
            self._local_since.pop(tid, None)
            self._env_miss_since.pop(tid, None)
            rec = self.task_manager.get(tid)
            if rec is not None:
                self._planned_add(rec.spec.resources, -1)
            # re-place AWAY from this starved node (reference:
            # spillback excludes the rejecting raylet)
            self._avoid_local.add(tid)
            moved.append(tid)

    def _reconcile_assigned(self) -> None:
        """Safety net for pipelined-lease commit races: entries parked
        on a worker that is dead, blocked, or idle (its release raced
        the commit) have no result-arrival left to ship them — recall
        so normal dispatch takes over.  Runs on the event loop's timed
        wake while any entries exist."""
        with self._cv:
            if self._assigned_total <= 0:
                return
        with self.pool._lock:
            workers = list(self.pool._workers)
        for w in workers:
            with self._cv:
                if not w.assigned:
                    continue
            if w.dead:
                self._recall_assigned(w, to_global=True)
            elif w.blocked or w.leased_task is None:
                self._recall_assigned(w)

    def _dispatch_next_assigned(self, worker: WorkerHandle) -> bool:
        """Send the next pipelined-lease task to a worker that just
        finished one.  Returns True when an exec frame shipped; False
        when the queue is empty (a failed dispatch recalls the
        remainder — its failure path already released the worker, which
        would otherwise strand them)."""
        while True:
            with self._cv:
                if not worker.assigned:
                    return False
                task_id, _t = worker.assigned.popleft()
                self._assigned_total -= 1
            rec = self.task_manager.get(task_id)
            if rec is None or rec.done:
                # completed while queued = cancelled; the cancel path
                # removed-or-refunded already (a refund HERE would
                # double-credit the CRM — see _recall_assigned, which
                # skips the same way)
                continue
            if self._dispatch(worker, rec):
                return True
            self._recall_assigned(worker)
            return False

    def _quick_dispatch_from_queue(self, worker: WorkerHandle) -> bool:
        """Result-chained dispatch (runs on the worker's reader
        thread): hand the just-freed worker the OLDEST placed
        default-env task whose resources fit, without waiting for a
        scheduling-loop wake.  Conservative by design — any
        complication (env task at the head, in-flight arg pulls,
        stopped node, resource miss) falls back to the event loop,
        which retains full responsibility for fairness across classes
        and env/pull handling."""
        if worker.dead or worker.blocked or worker.env_key is not None \
                or getattr(worker, "dedicated", False):
            return False
        with self._cv:
            if self._stopped or self._draining or not self._local_queue:
                return False
            # oldest class head (same order _drain_local visits)
            pick, oldest = None, float("inf")
            for key in self._local_queue.classes():
                for tid in self._local_queue.bucket(key):
                    t0 = self._local_since.get(tid, float("inf"))
                    if t0 < oldest:
                        oldest, pick = t0, tid
                    break               # head of this class only
            if pick is None or pick in self._pull_pending:
                return False
            rec = self.task_manager.get(pick)
            if rec is None or rec.done or rec.spec.runtime_env:
                return False
            if not self.crm.subtract(self.row, rec.spec.resources):
                return False
            try:
                self._local_queue.remove(pick)
            except ValueError:
                self.crm.add_back(self.row, rec.spec.resources)
                return False
            self._local_since.pop(pick, None)
            self._env_miss_since.pop(pick, None)
            self._planned_add(rec.spec.resources, -1)
        return self._dispatch(worker, rec)

    def _recall_assigned(self, worker: WorkerHandle,
                         to_global: bool = False,
                         avoid_local: bool = False) -> None:
        """Pull every not-yet-sent task back off a worker (blocked in a
        get, declared stale, or dying) and requeue it for dispatch
        elsewhere.  Resources return; placement is re-planned.
        ``avoid_local``: re-place AWAY from this node (stale-lease
        spillback — local requeue would just re-commit to the same
        wedged worker in a loop)."""
        with self._cv:
            spill = list(worker.assigned)
            worker.assigned.clear()
            self._assigned_total -= len(spill)
            if self._draining:
                to_global = True    # local requeue would lease here again
        for task_id, _t in spill:
            rec = self.task_manager.get(task_id)
            if rec is None or rec.done:
                continue        # cancelled while queued: the cancel
                # path refunded at removal (see _dispatch_next_assigned)
            self.crm.add_back(self.row, rec.spec.resources)
            if avoid_local:
                with self._cv:
                    self._avoid_local.add(task_id)
                self._enqueue(task_id)
            elif to_global:
                self._enqueue(task_id)
            else:
                with self._cv:
                    self._local_queue.append(task_id,
                                             rec.spec.resources.key())
                    self._local_since[task_id] = _clk.monotonic()
                    self._planned_add(rec.spec.resources, 1)
        if spill:
            self._notify_dirty()

    def _requeue_after_worker_loss(self, rec, worker: WorkerHandle) -> None:
        self.crm.add_back(self.row, rec.spec.resources)
        self._task_start.pop(rec.spec.task_id.binary(), None)
        worker.dead = True
        self._enqueue(rec.spec.task_id)

    def _seal_error_returns(self, rec, err) -> None:
        """Seal ``err`` into every live return (seal before complete —
        see the result handler); a streaming generator additionally
        finishes its stream with the error so blocked consumers wake."""
        for oid in rec.return_ids:
            if oid not in rec.dead_returns:
                self.store.put(oid, err)
        if rec.spec.num_returns == -1:
            self.task_manager.stream_finished(rec.spec.task_id, err)

    def _fail_unscheduled(self, rec, message: str) -> None:
        """Fail a task that never reached dispatch (no resources were
        subtracted, no worker leased)."""
        err = RayTaskError(rec.spec.function_descriptor, message)
        self._seal_error_returns(rec, err)
        self.task_manager.complete(rec.spec.task_id)

    def _finish_with_error(self, rec, error: RayTaskError,
                           worker: WorkerHandle | None) -> None:
        self._seal_error_returns(rec, error)
        self.task_manager.complete(rec.spec.task_id)
        self.crm.add_back(self.row, rec.spec.resources)
        if worker is not None:
            self.pool.release(worker)
        self._notify_dirty()

    # -- worker frame handling (runs on reader threads) ---------------------
    def _on_worker_message(self, worker: WorkerHandle, msg: tuple) -> None:
        kind = msg[0]
        am = self.actor_manager
        if am is not None:
            if am.on_worker_message(worker, msg):
                return
            if kind == "actor_create":
                from ..common.ids import ActorID
                unpacked = deserialize(msg[4])
                namespace, lifetime = None, None
                if len(unpacked) == 11:
                    (args, kwargs, max_restarts, max_task_retries, name,
                     res, strategy, runtime_env, concurrency, namespace,
                     lifetime) = unpacked
                elif len(unpacked) == 9:
                    (args, kwargs, max_restarts, max_task_retries, name,
                     res, strategy, runtime_env, concurrency) = unpacked
                else:       # pre-concurrency frame shape
                    (args, kwargs, max_restarts, max_task_retries, name,
                     res, strategy, runtime_env) = unpacked
                    concurrency = None
                parent_env = self._parent_env_of(worker)
                if parent_env:
                    # worker-created actors inherit the creating
                    # task/actor's env, like child tasks do
                    from .runtime_env import merge_runtime_env
                    runtime_env = merge_runtime_env(parent_env,
                                                    runtime_env)
                if namespace is None:   # worker default: job namespace
                    namespace = self.cluster.default_namespace
                am.create_actor(ActorID(msg[1]), msg[2], msg[3], args,
                                kwargs, max_restarts, max_task_retries,
                                name, resources=res, strategy=strategy,
                                runtime_env=runtime_env,
                                concurrency=concurrency,
                                namespace=namespace, lifetime=lifetime)
                return
            if kind == "actor_submit":
                from ..common.ids import ActorID
                unpacked = deserialize(msg[4])
                if len(unpacked) == 5:
                    args, kwargs, num_returns, trace_ctx, group = unpacked
                else:
                    args, kwargs, num_returns, trace_ctx = unpacked
                    group = None
                am.submit(ActorID(msg[1]), TaskID(msg[2]), msg[3], args,
                          kwargs, num_returns, trace_ctx=trace_ctx,
                          concurrency_group=group)
                return
            if kind == "actor_kill":
                from ..common.ids import ActorID
                am.kill(ActorID(msg[1]), no_restart=msg[2])
                return
            if kind == "named_actor":
                ns = msg[2] if len(msg) > 2 else None
                if ns is None:      # worker default: the job's namespace
                    ns = self.cluster.default_namespace
                aid = am.get_by_name(msg[1], ns)
                worker.send(("named_actor_reply",
                             aid.binary() if aid else None))
                return
        if kind in ("result", "result_x", "error"):
            task_id_bin = msg[1]
            with self._cv:
                entry = self._running.pop(task_id_bin, None)
            if entry is None:
                self.pool.release(worker)
                return
            task_id, _, pinned = entry
            self.store.unpin(pinned)    # task done: release shm arg pins
            rec = self.task_manager.get(task_id)
            t0 = self._task_start.pop(task_id_bin, None)
            if t0 is not None and rec is not None:
                trace = {}
                if rec.spec.trace_ctx is not None:
                    trace = {"trace_id": rec.spec.trace_ctx[0],
                             "parent_id": rec.spec.trace_ctx[1],
                             "span_id": rec.spec.task_id.hex()}
                self.cluster.events.span(
                    "task", rec.spec.function_descriptor[:16], t0,
                    _clk.now(), self.row, worker=worker.proc.pid,
                    status=kind, **trace)
            if rec is not None and not rec.done:
                # returns seal BEFORE complete(): a dropped ref whose
                # decref folds mid-handler must see either a pending
                # record (defer-to-seal) or a sealed object (reclaim now)
                # — marking done first opens a window where the counter
                # concludes the object will never seal and leaks it
                if kind == "result":
                    self._seal_contained(rec, msg[3] if len(msg) > 3
                                         else None)
                    self._seal_results(rec, msg[2])
                elif kind == "result_x":
                    self._seal_contained(rec, msg[3] if len(msg) > 3
                                         else None)
                    self._seal_results_x(rec, msg[2])
                else:
                    self._seal_error_returns(rec, deserialize(msg[2]))
                self.task_manager.complete(task_id)
                self.crm.add_back(self.row, rec.spec.resources)
            # max_calls worker recycling (reference: the executing
            # worker retires after N calls of the function — the
            # pressure valve for native-memory leaks): kill instead of
            # reuse; the pool's death-respawn replaces it and recalls
            # any pipelined tasks
            if rec is not None and rec.spec.max_calls > 0 \
                    and not worker.dedicated:
                fd = rec.spec.function_descriptor
                worker.fn_calls[fd] = worker.fn_calls.get(fd, 0) + 1
                if worker.fn_calls[fd] >= rec.spec.max_calls:
                    self.pool.kill_worker(worker)
                    self._notify_dirty()
                    return
            # pipelined lease: ship the next committed task from THIS
            # reader thread before anything else can steal the worker;
            # with no committed entry, chain straight into the oldest
            # queued task that fits (skips the event-loop wake — the
            # tiny-task hot path's dominant fixed cost)
            if not self._dispatch_next_assigned(worker) and \
                    not self._quick_dispatch_from_queue(worker):
                self.pool.release(worker)
            self._notify_dirty()
        elif kind == "get":
            oids = [self._oid(b) for b in msg[1]]
            timeout = msg[2] if len(msg) > 2 else None
            # descriptors: shm objects reply as (offset, size) for a
            # zero-copy read on the worker's own arena mapping
            if all(self.store.contains(o) for o in oids) and \
                    all(self._object_local(o) for o in oids):
                if self.remote_plane:
                    worker.send(("get_reply_x", "ok",
                                 self._remote_get_descs(oids)))
                    return
                descs = self.store.get_descriptors_blocking(oids)
                self._send_get_reply(worker, oids, descs)
                return
            # Blocking get: release the task's resources while the worker
            # waits (reference: CPU is returned during ray.get so dependent
            # tasks can run) and grow the pool if it is starved — otherwise
            # recursive fan-out deadlocks on worker slots.  Remote plasma
            # objects are pulled here at GET priority (reference:
            # PullManager prioritizes gets above wait/task-arg pulls).
            from .pull_manager import PullPriority
            rec = self._rec_of_worker(worker)
            self._enter_blocked(worker, rec)
            pulled = self.cluster.pull_manager.pull_blocking(
                oids, self.row, PullPriority.GET, timeout, self.store)
            if self.remote_plane:
                ok = pulled and self.store.get_raw_presence(
                    oids, timeout=timeout)
                self._exit_blocked(worker, rec)
                if not ok:
                    worker.send(("get_reply_x", "timeout", None))
                else:
                    worker.send(("get_reply_x", "ok",
                                 self._remote_get_descs(oids)))
                return
            descs = self.store.get_descriptors_blocking(
                oids, timeout=timeout) if pulled else None
            self._exit_blocked(worker, rec)
            if descs is None:
                worker.send(("get_reply", serialize(("timeout", None))))
            else:
                self._send_get_reply(worker, oids, descs)
        elif kind == "get_ack":
            # the worker finished its zero-copy reads of the oldest
            # outstanding get reply: release those pins (FIFO — the
            # single-threaded worker acks replies in receive order)
            with worker.pin_lock:
                batch = (worker.pending_get_pins.popleft()
                         if worker.pending_get_pins else None)
            if batch:
                self.store.unpin(batch)
        elif kind == "wait":
            oids = [self._oid(b) for b in msg[1]]
            num_returns = min(msg[2], len(oids))
            timeout = msg[3]
            # fast path: already satisfiable without blocking this reader
            ready, _ = self.store.wait(oids, num_returns, timeout=0)
            if len(ready) < num_returns and (timeout is None or timeout > 0):
                rec = self._rec_of_worker(worker)
                self._enter_blocked(worker, rec)
                ready, _ = self.store.wait(oids, num_returns,
                                           timeout=timeout)
                self._exit_blocked(worker, rec)
            # warm locality for satisfied waits (reference: wait triggers
            # pulls below get priority); readiness itself is presence-based
            from .pull_manager import PullPriority
            from .object_store import PLASMA_KINDS
            for o in ready:
                if not self._object_local(o):
                    kind, size = self.store.plasma_info(o)
                    if kind in PLASMA_KINDS:
                        self.cluster.pull_manager.request_pull(
                            o, size, self.row, PullPriority.WAIT)
            worker.send(("wait_reply",
                         serialize([o.binary() for o in ready])))
        elif kind == "stream_item":
            # ("stream_item", tid_bin, index, payload, contained):
            # one yielded item of a streaming generator seals NOW
            from ..common.ids import ObjectID as _OID
            tid = TaskID(msg[1])
            oid = _OID.for_task_return(tid, msg[2])
            rec = self.task_manager.get(tid)   # None for actor streams
            if self.task_manager.stream_accepts(tid) and \
                    (rec is None or oid not in rec.dead_returns):
                self._register_contained(oid, msg[4])
                self.cluster.seal_serialized(oid, msg[3], self.row)
                self.task_manager.stream_item_sealed(tid, msg[2])
        elif kind == "stream_item_x":
            # plane mode: the agent sealed the payload into its arena
            # (the descriptor is always ("p", oid_bin, size) — small
            # items stay plain stream_item frames)
            from ..common.ids import ObjectID as _OID
            tid = TaskID(msg[1])
            oid = _OID.for_task_return(tid, msg[2])
            rec = self.task_manager.get(tid)
            d = msg[3]
            if not self.task_manager.stream_accepts(tid) \
                    or (rec is not None and oid in rec.dead_returns) \
                    or d[0] != "p":
                # dropped item: the agent's arena copy is orphaned —
                # free it (mirrors _seal_results_x's dead-return path)
                if d[0] == "p" and self.plane_address is not None:
                    self.cluster.plane.free_on(self.plane_address,
                                               [oid])
            else:
                self._register_contained(oid, msg[4])
                self.cluster.directory.add_location(oid, self.row)
                self.store.put_remote(oid, d[2])
                self.task_manager.stream_item_sealed(tid, msg[2])
        elif kind == "stream_end":
            tid = TaskID(msg[1])
            if len(msg) > 3 and msg[3]:
                # the producer STALLED out (no consumer ack for the
                # orphan window): finish the stream with a loud error
                # and reclaim its sealed payloads — a slow consumer
                # fails visibly (the errored state is retained) and
                # nothing big leaks in a long-lived driver
                orphans = self.task_manager.stream_abandon(
                    tid, RayTaskError(
                        "stream", "stream producer stalled: no "
                        "consumer ack within the orphan window (the "
                        "consuming side died, or took >10 minutes "
                        "between items)"))
                for oid in orphans:
                    if self.store.contains(oid):
                        # counter-routed so contained refs release too
                        self.cluster.ref_counter.force_reclaim(oid)
            else:
                self.task_manager.stream_finished(tid)
        elif kind == "stream_wait":
            # a WORKER consuming a stream: block like the get path
            # (resources return while it waits; this reader thread is
            # the worker's, so frames for it queue behind — the
            # existing blocking-get discipline)
            tid, index, timeout = TaskID(msg[1]), msg[2], msg[3]
            # fast path (like get): already satisfiable => no blocked-
            # worker dance (resource return/re-debit + recall per item)
            sealed, done, err, known = self.task_manager.wait_stream(
                tid, index, 0)
            if not (sealed > index or done):
                rec = self._rec_of_worker(worker)
                self._enter_blocked(worker, rec)
                sealed, done, err, known = \
                    self.task_manager.wait_stream(tid, index, timeout)
                self._exit_blocked(worker, rec)
            worker.send(("stream_wait_reply", sealed, done,
                         serialize(err) if err is not None else None,
                         known))
        elif kind == "stream_ack_up":
            self.cluster.stream_ack(TaskID(msg[1]), msg[2])
        elif kind == "stream_close_up":
            self.cluster.stream_close(TaskID(msg[1]), msg[2])
        elif kind == "named_list":
            am2 = self.actor_manager
            worker.send(("named_list_reply",
                         am2.list_named(msg[1])
                         if am2 is not None else []))
        elif kind == "stacks_reply":
            # live stack sample answered by the worker's reader thread
            self.cluster._on_stacks_reply(msg[1], self.row,
                                          worker.index, msg[2])
        elif kind == "refs":
            # this worker's batched local incref/decref events fold
            # against its holder entry (distributed refcounting)
            self.cluster.ref_counter.apply_batch(msg[1],
                                                 self._holder_of(worker))
        elif kind == "put":
            oid = self._oid(msg[1])
            self._register_contained(oid, msg[3] if len(msg) > 3 else ())
            self.cluster.ref_counter.set_owner(oid,
                                              self._holder_of(worker))
            self.cluster.seal_serialized(oid, msg[2], self.row)
        elif kind == "put_x":
            # a plane agent already sealed the put payload into its own
            # arena: record metadata only (location before seal)
            oid = self._oid(msg[1])
            self._register_contained(oid, msg[3] if len(msg) > 3 else ())
            self.cluster.ref_counter.set_owner(oid,
                                              self._holder_of(worker))
            self.cluster.directory.add_location(oid, self.row)
            self.store.put_remote(oid, msg[2])
        elif kind == "submit":
            spec = deserialize(msg[1])
            fn_id, fn_bytes = msg[2], msg[3]
            if fn_bytes is not None and fn_id not in self._fn_registry:
                self._fn_registry[fn_id] = fn_bytes
            # no driver-side ObjectRefs for the results: the live refs
            # are in the submitting WORKER process, whose own counter
            # streams them here against its holder entry ("refs"
            # frames) — the worker owns these returns and its holder
            # keeps them alive until its refs die (or it does)
            from ..common.ids import ObjectID as _OID
            holder = self._holder_of(worker)
            for i in range(spec.num_returns):
                self.cluster.ref_counter.set_owner(
                    _OID.for_task_return(spec.task_id, i + 1), holder)
            parent_env = self._parent_env_of(worker)
            if parent_env:
                # children inherit their PARENT task/actor's env, not
                # just the job's (reference inheritance semantics)
                from .runtime_env import merge_runtime_env
                spec.runtime_env = merge_runtime_env(parent_env,
                                                     spec.runtime_env)
            self.submit(spec)   # shares the job-env merge intake
        elif kind == "pg_create":
            from ..common.ids import PlacementGroupID
            from ..scheduling.bundles import PlacementStrategy
            bundles, strategy_name, name = deserialize(msg[2])
            self.cluster.pg_manager.create(
                PlacementGroupID(msg[1]), bundles,
                PlacementStrategy[strategy_name], name=name)
        elif kind == "pg_remove":
            from ..common.ids import PlacementGroupID
            self.cluster.pg_manager.remove(PlacementGroupID(msg[1]))
        elif kind == "kv":
            # ("kv", op, key, value, namespace, overwrite)
            #   -> ("kv_reply", result, error_or_None)
            # A reply goes back even on failure: the worker blocks in
            # _recv_reply with no timeout, so a swallowed exception here
            # (bad value type, unknown op) would wedge it forever.
            try:
                result = self.cluster.kv.dispatch(
                    msg[1], msg[2], msg[3], msg[4], msg[5])
                worker.send(("kv_reply", result, None))
            except Exception as e:      # noqa: BLE001
                worker.send(("kv_reply", None,
                             f"{type(e).__name__}: {e}"))

    def _holder_of(self, worker: WorkerHandle) -> tuple:
        """This worker process's refcount holder key (pool indexes are
        monotonic, so the key is never reused on this raylet)."""
        return ("w", self.row, worker.index)

    def _register_contained(self, parent, contained_bins) -> None:
        if contained_bins:
            self.cluster.ref_counter.add_contained(
                parent, [self._oid(b) for b in contained_bins])

    def _seal_contained(self, rec, contained) -> None:
        """Refs pickled inside result payloads stay alive until the
        enclosing return object is reclaimed (borrow-on-return)."""
        if not contained:
            return
        for oid, inner in zip(rec.return_ids, contained):
            if inner and oid not in rec.dead_returns:
                self._register_contained(oid, inner)

    def _seal_results(self, rec, payloads) -> None:
        """Seal a task's serialized return payloads (size-routed, with
        pre-registered locations — ``Cluster.seal_serialized``)."""
        for oid, data in zip(rec.return_ids, payloads):
            if oid in rec.dead_returns:
                continue        # reclaimed while out of scope: a re-seal
                # would live forever (no refs remain to ever decref it)
            self.cluster.seal_serialized(oid, data, self.row)

    def _seal_results_x(self, rec, descs) -> None:
        """Seal plane-mode return descriptors: ("p", oid_bin, size) means
        the agent already sealed the payload into ITS arena — the head
        records metadata only (directory location BEFORE the remote seal,
        the seal_serialized ordering); ("v", bytes) rode in-band and
        seals here, born on the HEAD row (that is where the bytes are)."""
        head_row = self.cluster.head().row
        for oid, d in zip(rec.return_ids, descs):
            if oid in rec.dead_returns:
                if d[0] == "p" and self.plane_address is not None:
                    # nobody will ever reference it: free the agent copy
                    self.cluster.plane.free_on(self.plane_address, [oid])
                continue
            if d[0] == "p":
                self.cluster.directory.add_location(oid, self.row)
                self.store.put_remote(oid, d[2])
            else:
                self.cluster.seal_serialized(oid, d[1], head_row)

    def _remote_get_descs(self, oids) -> list:
        """Get-reply descriptors for a plane-backed remote worker: plasma
        objects with a copy on this row ship by reference ("r" — the
        agent resolves them against its own arena, bytes never transit
        the head); head-resident bytes inline under the pin; in-band
        values ship serialized (the relay never unpickles user data)."""
        from .object_store import PLASMA_KINDS
        out = []
        for o in oids:
            kind, _size = self.store.plasma_info(o)
            if kind in PLASMA_KINDS and \
                    self.cluster.directory.has_location(o, self.row):
                out.append(("r", o.binary()))
                continue
            try:
                desc = self.store.descriptor_of(o)
            except KeyError:
                # vanished post-wait (reclaim race): surface as an error
                from .object_store import ObjectLostError
                desc = ("v", RayTaskError(
                    "get", f"object {o.hex()[:12]} was reclaimed",
                    ObjectLostError(o.hex())))
            if desc[0] == "s":
                out.append(("b", self.store.inline_bytes(o, desc)))
            elif desc[0] == "v":
                out.append(("vb", serialize(desc[1])))
            else:
                out.append(desc)
        return out

    def _send_get_reply(self, worker: WorkerHandle, oids, descs) -> None:
        """Ship get descriptors; shm descriptors were pinned by the store,
        so record them for release on the worker's get_ack (every reply
        with shm descriptors gets exactly one ack)."""
        if self.inline_objects:
            # remote worker: no shared arena, so copy under the pin and
            # release now — in-band descriptors are never acked
            descs = [("b", self.store.inline_bytes(o, d))
                     if d[0] == "s" else d
                     for o, d in zip(oids, descs)]
        shm_pins = [(o, d[1]) for o, d in zip(oids, descs) if d[0] == "s"]
        if shm_pins:
            with worker.pin_lock:
                if worker.no_more_pins:
                    # worker is being drained/killed: drop the reply (it
                    # will never be read) and release the pins now
                    self.store.unpin(shm_pins)
                    return
                worker.pending_get_pins.append(shm_pins)
        if not worker.send(("get_reply", serialize(("ok", descs)))) \
                and shm_pins:
            with worker.pin_lock:
                try:
                    worker.pending_get_pins.remove(shm_pins)
                except ValueError:
                    return          # a concurrent drain already released
            self.store.unpin(shm_pins)

    @staticmethod
    def _oid(binary: bytes):
        from ..common.ids import ObjectID
        return ObjectID(binary)

    def _object_local(self, oid) -> bool:
        """True when a get/dispatch on this node needs no pull: in-band
        value, or a plasma object with a local copy."""
        from .object_store import PLASMA_KINDS
        kind, _ = self.store.plasma_info(oid)
        return kind not in PLASMA_KINDS or \
            self.cluster.directory.has_location(oid, self.row)

    def _drain_worker_pins(self, worker: WorkerHandle) -> None:
        """Release every un-acked get-reply pin of a dead/draining worker
        and latch out further appends (a reader thread may still be
        finishing a blocking get for it)."""
        with worker.pin_lock:
            worker.no_more_pins = True
            batches = list(worker.pending_get_pins)
            worker.pending_get_pins.clear()
        for batch in batches:
            self.store.unpin(batch)

    def _rec_of_worker(self, worker: WorkerHandle):
        """TaskRecord of the task the worker is currently executing."""
        if worker.leased_task is None:
            return None
        with self._cv:
            entry = self._running.get(worker.leased_task)
        return self.task_manager.get(entry[0]) if entry is not None else None

    def _enter_blocked(self, worker: WorkerHandle, rec) -> None:
        """Worker blocks in get/wait: return its task's resources so
        dependent tasks can run, and grow the pool if starved.  Tasks
        pipelined behind the blocker are recalled and dispatch
        elsewhere — left queued they could deadlock (the blocker may be
        waiting on exactly the task parked behind it)."""
        worker.blocked = True
        self._recall_assigned(worker)
        if rec is not None:
            self.crm.add_back(self.row, rec.spec.resources)
            self._notify_dirty()
        self.pool.grow_for_blocked()

    def _exit_blocked(self, worker: WorkerHandle, rec) -> None:
        """Re-acquire before resuming (waits for capacity like the
        reference's worker unblock path; bounded oversubscription is
        preferred over a stuck reader if capacity never frees)."""
        if rec is not None:
            self._reacquire(rec.spec.resources)
        worker.blocked = False

    def _reacquire(self, resources: ResourceRequest,
                   patience: float = 5.0) -> None:
        """Event-driven re-debit after a blocking get: parks on the CRM's
        release condition (no polling); past ``patience`` it
        oversubscribes rather than wedging — the matching add_back at task
        completion rebalances."""
        if not self.crm.wait_subtract(self.row, resources, patience):
            self.crm.force_subtract(self.row, resources)

    def _on_worker_death(self, worker: WorkerHandle) -> None:
        self._drain_worker_pins(worker)
        # fate-sharing: every ref this worker process held dies with it
        self.cluster.ref_counter.holder_gone(self._holder_of(worker))
        # not-yet-sent pipelined tasks were never at risk: requeue them
        self._recall_assigned(worker, to_global=True)

        if self.actor_manager is not None and \
                self.actor_manager.on_worker_death(worker):
            return
        task_id_bin = worker.leased_task
        if task_id_bin is None:
            return
        with self._cv:
            entry = self._running.pop(task_id_bin, None)
        if entry is None:
            return
        task_id, _, pinned = entry
        self.store.unpin(pinned)
        self._task_start.pop(task_id_bin, None)
        rec = self.task_manager.get(task_id)
        if rec is None:
            return
        self.crm.add_back(self.row, rec.spec.resources)
        if rec.done:
            # completed elsewhere (a force-cancel sealed it before the
            # kill): only the resource refund above was still owed —
            # re-sealing would clobber the cancellation error
            self._notify_dirty()
            return
        if self.task_manager.should_retry(task_id):
            self._enqueue(task_id)
        else:
            err = RayTaskError(
                rec.spec.function_descriptor,
                "worker died", WorkerCrashedError(
                    f"worker {worker.index} died executing "
                    f"{rec.spec.function_descriptor}"))
            self._seal_error_returns(rec, err)
            self.task_manager.complete(task_id)
        self._notify_dirty()

    # -- cancel / teardown --------------------------------------------------
    def _cancel_seal_and_complete(self, task_id: TaskID) -> None:
        """Seal the cancellation error, THEN mark done (seal-before-
        complete, like the result handler)."""
        from .serialization import TaskCancelledError
        rec = self.task_manager.get(task_id)
        if rec is None or rec.done:
            return
        err = RayTaskError(rec.spec.function_descriptor, "cancelled",
                           TaskCancelledError())
        self._seal_error_returns(rec, err)
        self.task_manager.complete(task_id)

    def stream_ack(self, task_id: TaskID, consumed: int) -> bool:
        """Relay a consumer's progress to the generator's worker so its
        backpressure window slides; False when the task is not running
        here (best-effort — a stalled ack only pauses the producer)."""
        with self._cv:
            entry = self._running.get(task_id.binary())
        if entry is None:
            return False
        entry[1].send(("stream_ack", task_id.binary(), consumed))
        return True

    def stream_cancel(self, task_id: TaskID) -> bool:
        """Cooperative stop for a running generator: it ends its stream
        at the next backpressure check instead of yielding further."""
        with self._cv:
            entry = self._running.get(task_id.binary())
        if entry is None:
            return False
        entry[1].send(("stream_cancel", task_id.binary()))
        return True

    def cancel(self, task_id: TaskID, force: bool = False) -> bool:
        from .serialization import TaskCancelledError
        with self._cv:
            if task_id in self._local_queue:
                rec0 = self.task_manager.get(task_id)
                self._local_queue.remove(task_id)
                self._local_since.pop(task_id, None)
                self._env_miss_since.pop(task_id, None)
                if rec0 is not None:
                    self._planned_add(rec0.spec.resources, -1)
                self._cancel_seal_and_complete(task_id)
                return True
            if task_id in self._queue:
                self._queue.remove(task_id)
                self._avoid_local.discard(task_id)
                self._cancel_seal_and_complete(task_id)
                return True
            if self._waiting.pop(task_id, None) is not None:
                # dep-waiting: resolve its refs with the cancellation error
                # (a later _dep_ready finds no entry and is a no-op)
                self._cancel_seal_and_complete(task_id)
                return True
            entry = self._running.get(task_id.binary())
        if entry is None:
            # committed to a worker's pipelined lease but not yet sent:
            # remove + refund; sealing completes the record so a racing
            # _dispatch_next_assigned skips it
            with self.pool._lock:
                workers = list(self.pool._workers)
            for w in workers:
                with self._cv:
                    match = [e for e in w.assigned if e[0] == task_id]
                    for e in match:
                        w.assigned.remove(e)
                        self._assigned_total -= 1
                if match:
                    rec0 = self.task_manager.get(task_id)
                    if rec0 is not None:
                        self.crm.add_back(self.row, rec0.spec.resources)
                    self._cancel_seal_and_complete(task_id)
                    return True
        if entry is not None and force:
            # seal FIRST (exactly like the agent-leased branch below):
            # the worker-death bookkeeping must find the record done
            # and skip its retry — killing first would race the death
            # path into resubmitting the cancelled task
            self._cancel_seal_and_complete(task_id)
            self.pool.kill_worker(entry[1])  # death path does bookkeeping
            return True
        # agent-leased task (autonomous dispatch): ask the agent what
        # state it is in, then mirror the head-local semantics —
        # a QUEUED task cancels outright; a RUNNING one cancels only
        # under force (the kill); non-force running returns False like
        # the local path.  Sealing here may race a just-completed
        # done-sync: _cancel_seal_and_complete no-ops on a done record,
        # and AgentHub._sync_done frees agent-arena descs of a record
        # completed elsewhere, so neither side leaks.
        rec_a = self.agent_inflight.get(task_id)
        if rec_a is not None:
            sp = getattr(self.pool, "_spawner", None)
            if force:
                # seal FIRST: the kill's worker-death 'retry' handback
                # must find rec.done and be skipped — sealing after
                # would race it into resubmitting the cancelled task
                self.agent_inflight.pop(task_id, None)
                self._cancel_seal_and_complete(task_id)
                if sp is not None and hasattr(sp, "cancel_remote"):
                    sp.cancel_remote(task_id.binary(), True)
                return True
            verdict = None
            if sp is not None and hasattr(sp, "cancel_remote"):
                verdict = sp.cancel_remote(task_id.binary(), False)
            if verdict == "dequeued":
                # never dispatched: no handback can race this seal
                self.agent_inflight.pop(task_id, None)
                self._cancel_seal_and_complete(task_id)
                return True
            return False        # running + non-force: like local path
        return False

    def start_graceful_drain(self) -> None:
        """ALIVE -> DRAINING: stop committing new leases here while
        running tasks finish.  Unlike ``drain_for_removal`` the pool and
        event loop stay up: queued and pipelined-but-unsent work
        re-enters GLOBAL scheduling, and because the CRM drain mask
        makes this row infeasible to every policy, it lands elsewhere.
        Idempotent."""
        with self._cv:
            if self._draining:
                return
            self._draining = True
        # pipelined-but-unsent leases come back and re-place globally
        with self.pool._lock:
            workers = list(self.pool._workers)
        for w in workers:
            self._recall_assigned(w, to_global=True)
        with self._cv:
            requeue = list(self._local_queue)
            self._local_queue.clear()
            for task_id in requeue:
                self._local_since.pop(task_id, None)
                self._env_miss_since.pop(task_id, None)
                # in-flight arg pulls: the entry goes now, so a late
                # _pull_done finds nothing and no-ops
                self._pull_pending.pop(task_id, None)
                rec = self.task_manager.get(task_id)
                if rec is not None:
                    self._planned_add(rec.spec.resources, -1)
                self._queue.append(task_id)
            self._dirty = True
            self._cv.notify_all()

    def is_draining(self) -> bool:
        return self._draining

    def drain_empty(self) -> bool:
        """Nothing left that holds this node's resources or would die
        with it: no backlog awaiting (re-)placement, no leases, no
        running tasks, no agent-leased work, no live actor workers.
        Dep-WAITING tasks are deliberately excluded — they hold no
        lease, and forced removal reroutes their readiness callbacks
        through the fallback raylet."""
        with self._cv:
            busy = (self._queue or self._local_queue or self._running
                    or self._pull_pending or self.agent_inflight
                    or self._assigned_total)
        if busy:
            return False
        with self.pool._lock:
            return not any(w.dedicated and not w.dead
                           for w in self.pool._workers)

    def drain_for_removal(self, fallback: "Raylet") -> None:
        """Node death: fail/retry running tasks, reroute queued ones,
        restart-or-fail actors placed here, keep dep-waiting tasks alive
        (their readiness callbacks re-route to the fallback raylet)."""
        # recall never-sent pipelined tasks FIRST so the queue capture
        # below reroutes them with everything else
        with self.pool._lock:
            pool_workers = list(self.pool._workers)
        for w in pool_workers:
            self._recall_assigned(w)
        with self._cv:
            self._stopped = True
            self._removal_fallback = fallback
            queued = list(self._queue) + list(self._local_queue)
            self._queue.clear()
            self._local_queue.clear()
            self._local_since.clear()
            self._env_miss_since.clear()
            self._avoid_local.clear()
            running = list(self._running.items())
            self._running.clear()
            self._cv.notify_all()
        if self.actor_manager is not None:
            self.actor_manager.fail_actors_on_pool(self.pool)
        # the pool shutdown suppresses per-worker death callbacks, so
        # release descriptor pins (get replies + running-task args) here
        with self.pool._lock:
            workers = list(self.pool._workers)
        for w in workers:
            self._drain_worker_pins(w)
            self.cluster.ref_counter.holder_gone(self._holder_of(w))
        for task_id in queued:
            fallback.enqueue_forwarded(task_id)
        # tasks the agent leased autonomously die with the node too:
        # their done-sync will never arrive, so retry or fail them NOW
        # (exactly the running-task semantics below)
        agent_tasks = list(self.agent_inflight.values())
        self.agent_inflight.clear()
        self.agent_local_cu = None
        for rec in agent_tasks:
            task_id = rec.spec.task_id
            if rec.done:
                continue
            if self.task_manager.should_retry(task_id):
                fallback.enqueue_forwarded(task_id)
            else:
                err = RayTaskError(
                    rec.spec.function_descriptor, "node removed",
                    WorkerCrashedError("node died with agent-leased "
                                       "task running"))
                self._seal_error_returns(rec, err)
                self.task_manager.complete(task_id)
        for _bin, (task_id, _w, pinned) in running:
            self.store.unpin(pinned)
            if self.task_manager.should_retry(task_id):
                fallback.enqueue_forwarded(task_id)
            else:
                rec = self.task_manager.get(task_id)
                if rec is None:
                    continue
                err = RayTaskError(
                    rec.spec.function_descriptor, "node removed",
                    WorkerCrashedError("node died"))
                self._seal_error_returns(rec, err)
                self.task_manager.complete(task_id)
        self.pool.shutdown()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self.pool.shutdown()
