"""PullManager: receiver-driven object transfer with a bandwidth cost model.

Reference parity: upstream's ``PullManager`` (``src/ray/object_manager/
pull_manager.cc``) — pull requests prioritized get > wait > task-arg,
activated under an in-flight memory quota, sources chosen against
per-link cost accounting; ``ObjectManager`` push/pull moves the chunks
(SURVEY.md §1 layer 6, §3.3 — the cost model BASELINE.json's north star
names explicitly; mount empty).

TPU-first: source selection for an activation batch is one dense device
computation over the node-bandwidth matrix (``ops/pull_kernel.py``) —
the matrix lives in HBM next to the scheduler state; small batches use
the bit-identical numpy oracle (same backend-switch pattern as the
scheduler, invisible to callers).

The simulated-cluster form (one shared arena, like upstream's
``cluster_utils.Cluster`` on one machine) makes a "transfer" a directory
update + byte accounting, optionally paced by a simulated link rate
(``pull_transfer_sim_gbps``) so quota/backpressure behavior is testable.
"""

from __future__ import annotations

import enum
import heapq
import threading
from collections import deque

import numpy as np

from ..common.config import get_config
from ..common.ids import ObjectID
from ..common import clock as _clk


class PullPriority(enum.IntEnum):
    """Activation order (reference: get > wait > task arg)."""
    GET = 0
    WAIT = 1
    TASK_ARG = 2


class PullManager:
    def __init__(self, cluster):
        self._cluster = cluster
        cfg = get_config()
        self._quota = cfg.pull_manager_max_inflight_mb * (1 << 20)
        self._sim_gbps = cfg.pull_transfer_sim_gbps
        self._device_min = cfg.pull_device_batch_min
        self._n_threads = max(1, cfg.object_transfer_threads)
        self._cv = threading.Condition()
        # pending requests: key (oid, dest_row) -> state dict
        self._requests: dict[tuple, dict] = {}
        self._heap: list = []               # (priority, seq, key)
        self._seq = 0
        self._active: deque = deque()       # (key, src_row) awaiting transfer
        self._inflight_bytes = 0
        # per-SOURCE-row bytes in flight (KB), feeding the cost model's
        # derating input: the fix for concurrent pulls all piling onto
        # the same "cheapest" replica.  Guarded by ``self._cv``.
        self._infl_kb_rows: dict[int, int] = {}
        self._stop = False
        self._threads: list[threading.Thread] = []
        # stats
        self.num_pulls = 0
        self.bytes_pulled = 0
        self.num_failed = 0
        self.device_batches = 0
        self.oracle_batches = 0

    # -- request side --------------------------------------------------------
    def request_pull(self, object_id: ObjectID, size: int, dest_row: int,
                     priority: PullPriority,
                     callback=None) -> bool:
        """Ask for a copy of ``object_id`` at ``dest_row``.  Returns True
        if already satisfied (no pull needed); otherwise queues and later
        invokes ``callback(ok: bool)`` (ok=False when the object is lost).
        Requests for the same (object, dest) coalesce."""
        directory = self._cluster.directory
        if directory.has_location(object_id, dest_row) or \
                not directory.is_tracked(object_id):
            # local already, or not a plasma object (in-band values ship
            # with specs; poisoned/lost entries are in-band errors)
            if callback is not None:
                callback(True)
            return True
        key = (object_id, dest_row)
        with self._cv:
            req = self._requests.get(key)
            if req is not None:
                if callback is not None:
                    req["callbacks"].append(callback)
                # escalate priority if a stronger waiter arrives
                if priority < req["priority"] and not req["active"]:
                    req["priority"] = priority
                    self._seq += 1
                    heapq.heappush(self._heap,
                                   (int(priority), self._seq, key))
                return False
            self._seq += 1
            self._requests[key] = {
                "size": max(int(size), 1),
                "priority": priority,
                "callbacks": [callback] if callback is not None else [],
                "active": False,
            }
            heapq.heappush(self._heap, (int(priority), self._seq, key))
            self._ensure_thread_locked()
            self._activate_locked()
        return False

    def pull_blocking(self, object_ids, dest_row: int,
                      priority: PullPriority, timeout: float | None,
                      store) -> bool:
        """Wait until every object exists AND is local to ``dest_row``
        (pulling as needed).  Lost objects count as done — their poisoned
        in-band error surfaces at the subsequent get.  False on timeout."""
        state = {"left": len(object_ids)}
        done = threading.Event()
        lock = threading.Lock()
        if not object_ids:
            return True

        def one_done(_ok: bool) -> None:
            with lock:
                state["left"] -= 1
                if state["left"] == 0:
                    done.set()

        def on_present(oid):
            from .object_store import PLASMA_KINDS
            kind, size = store.plasma_info(oid)
            if kind in PLASMA_KINDS:
                self.request_pull(oid, size, dest_row, priority,
                                  callback=one_done)
            else:
                one_done(True)

        for oid in object_ids:
            store.on_ready(oid, on_present)
        if done.wait(timeout):
            return True
        # timed out: deregister presence listeners so abandoned gets do
        # not leak closures (or fire phantom pulls later)
        for oid in object_ids:
            store.cancel_on_ready(oid, on_present)
        return False

    # -- activation (quota + source selection) -------------------------------
    def _ensure_thread_locked(self) -> None:
        self._threads = [t for t in self._threads if t.is_alive()]
        while len(self._threads) < self._n_threads:
            t = threading.Thread(
                target=self._transfer_loop, daemon=True,
                name=f"pull-manager-{len(self._threads)}")
            t.start()
            self._threads.append(t)

    def _activate_locked(self) -> None:
        """Move queued requests into the active transfer set while the
        in-flight byte quota allows; pick sources for the whole batch in
        one cost-model evaluation (device kernel for large batches)."""
        batch_keys = []
        while self._heap:
            prio, _seq, key = self._heap[0]
            req = self._requests.get(key)
            if req is None or req["active"] or prio > req["priority"]:
                heapq.heappop(self._heap)       # stale heap entry
                continue
            if self._inflight_bytes + req["size"] > self._quota and \
                    self._inflight_bytes > 0:
                break                           # quota: wait for completions
            heapq.heappop(self._heap)
            req["active"] = True
            self._inflight_bytes += req["size"]
            batch_keys.append(key)
        if not batch_keys:
            return
        srcs = self._choose_sources(batch_keys)
        for key, src in zip(batch_keys, srcs):
            if src < 0:
                # no live copy anywhere: the object is lost
                self._fail_locked(key)
                continue
            src = int(src)
            req = self._requests[key]
            req["src_row"] = src
            self._infl_kb_rows[src] = self._infl_kb_rows.get(src, 0) \
                + max(req["size"] >> 10, 1)
            self._active.append((key, src))
        self._cv.notify_all()

    def _choose_sources(self, keys: list[tuple]) -> np.ndarray:
        """Best source per request via the bandwidth cost model, derated
        by the bytes already in flight FROM each candidate (caller holds
        the lock, so the ledger snapshot is consistent with the batch)."""
        directory = self._cluster.directory
        bw = self._cluster.bandwidth_mbps
        n = bw.shape[0]
        oids = [k[0] for k in keys]
        dest = np.array([k[1] for k in keys], dtype=np.int32)
        sizes_kb = np.array(
            [max(self._requests[k]["size"] >> 10, 1) for k in keys],
            dtype=np.int32)
        loc = directory.location_matrix(oids, n)
        infl = self._inflight_kb_locked(n)
        if len(keys) >= self._device_min:
            from ..ops.pull_kernel import choose_sources_np
            self.device_batches += 1
            src, _cost = choose_sources_np(loc, bw, dest, sizes_kb, infl)
        else:
            from ..ops.pull_kernel import choose_sources_oracle
            self.oracle_batches += 1
            src, _cost = choose_sources_oracle(loc, bw, dest, sizes_kb,
                                               infl)
        return src

    def _inflight_kb_locked(self, n: int) -> np.ndarray:
        infl = np.zeros(n, dtype=np.int32)
        for row, kb in self._infl_kb_rows.items():
            if 0 <= row < n:
                infl[row] = min(kb, 2**31 - 1)
        return infl

    def inflight_kb(self, n: int) -> np.ndarray:
        """Per-source-row KB in flight — the broadcast coordinator feeds
        this into its fan-out kernel so tree shaping sees pull load."""
        with self._cv:
            return self._inflight_kb_locked(n)

    def _release_src_locked(self, req: dict) -> None:
        """Return an activated request's bytes to its source row's
        in-flight ledger (caller holds the lock)."""
        src = req.pop("src_row", None)
        if src is None:
            return
        left = self._infl_kb_rows.get(src, 0) - max(req["size"] >> 10, 1)
        if left > 0:
            self._infl_kb_rows[src] = left
        else:
            self._infl_kb_rows.pop(src, None)

    def _fail_locked(self, key: tuple) -> None:
        req = self._requests.pop(key, None)
        if req is None:
            return
        if req["active"]:
            self._inflight_bytes -= req["size"]
            self._release_src_locked(req)
        self.num_failed += 1
        cbs = req["callbacks"]
        if cbs:
            # callbacks run without the lock held (they may re-enter)
            threading.Thread(target=lambda: [cb(False) for cb in cbs],
                             daemon=True).start()

    # -- transfer loop -------------------------------------------------------
    def _transfer_loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._active:
                    self._cv.wait()
                if self._stop:
                    return
                key, src = self._active.popleft()
                # CLAIM the request while still holding the lock: a
                # concurrent on_objects_lost can no longer fail it, so
                # completion happens exactly once
                req = self._requests.pop(key, None)
            if req is None:
                continue
            oid, dest = key
            # the object may have been lost mid-transfer (source node
            # died): a lost object is untracked — do not resurrect it
            ok = self._cluster.directory.is_tracked(oid)
            if ok:
                ok = self._move_bytes(oid, dest, src, req["size"])
                if not ok and self._cluster.directory.is_tracked(oid) \
                        and req.get("attempts", 0) < 2:
                    # transient transfer failure (chunk RPC timeout,
                    # spill race) on a LIVE object: re-queue for another
                    # source-selection round instead of surfacing a
                    # bogus permanent loss to the waiters
                    req["attempts"] = req.get("attempts", 0) + 1
                    _clk.sleep(0.2 * req["attempts"])
                    with self._cv:
                        self._inflight_bytes -= req["size"]
                        self._release_src_locked(req)
                        dup = self._requests.get(key)
                        if dup is not None:
                            # a fresh request for the same key arrived
                            # mid-transfer: merge instead of clobbering
                            dup["callbacks"].extend(req["callbacks"])
                            dup["priority"] = min(dup["priority"],
                                                  req["priority"])
                        else:
                            req["active"] = False
                            self._requests[key] = req
                            self._seq += 1
                            heapq.heappush(
                                self._heap,
                                (int(req["priority"]), self._seq, key))
                        self._activate_locked()
                    continue
            if ok:
                # bytes land BEFORE the directory update: a callback
                # (task dispatch, get) must never observe a location
                # whose plane cannot serve the object yet
                self._cluster.directory.add_location(oid, dest)
            with self._cv:
                self._inflight_bytes -= req["size"]
                self._release_src_locked(req)
                if ok:
                    self.num_pulls += 1
                    self.bytes_pulled += req["size"]
                else:
                    self.num_failed += 1
                self._activate_locked()
            for cb in req["callbacks"]:
                cb(ok)

    def _move_bytes(self, oid, dest: int, src: int, size: int) -> bool:
        """Execute one transfer.  Simulated rows share the head arena
        (the transfer is a directory update, optionally paced); rows
        with a plane address move real chunks arena-to-arena — payload
        bytes flow source→destination directly, never through here.

        Beyond the cost-model-chosen primary ``src``, every OTHER
        directory replica's plane address rides along: the destination
        plane stripes chunk ranges across them (and fails over within
        the transfer when the primary dies mid-stripe)."""
        # an ACTIVE broadcast of this object grafts the pull onto the
        # relay tree (one leaf join) instead of opening an independent
        # stream against the cost model's favorite replica
        broadcasts = getattr(self._cluster, "broadcasts", None)
        if broadcasts is not None and broadcasts.join(oid, dest):
            return True
        planes = self._cluster.planes
        src_addr = planes.get(src)
        dest_addr = planes.get(dest)
        if src_addr is None and dest_addr is None:
            if self._sim_gbps > 0:
                _clk.sleep(size / (self._sim_gbps * 1e9))
            return True
        plane = self._cluster.plane
        if src_addr is None:
            # source shares the head store: serve from the head's plane
            src_addr = plane.serve_address
            if src_addr is None and dest_addr is not None:
                return False    # head store is not being served
        extra = self._replica_addrs(oid, dest, exclude=src_addr)
        if dest_addr is None:
            # destination shares the head store: fetch here
            return plane.pull_into_local(oid, size, src_addr, extra)
        return plane.request_remote_pull(dest_addr, oid, size, src_addr,
                                         extra)

    def _replica_addrs(self, oid, dest: int,
                       exclude: str | None) -> tuple:
        """Plane addresses of every directory replica besides the
        primary (striping candidates), destination excluded."""
        planes = self._cluster.planes
        head_addr = self._cluster.plane.serve_address
        out = []
        for row in self._cluster.directory.locations(oid):
            if row == dest:
                continue
            addr = planes.get(row)
            if addr is None:
                addr = head_addr    # head-resident replica
            if addr is not None and addr != exclude and addr not in out:
                out.append(addr)
        return tuple(out)

    # -- loss / teardown -----------------------------------------------------
    def on_objects_lost(self, object_ids) -> None:
        lost = set(object_ids)
        with self._cv:
            for key in [k for k in self._requests if k[0] in lost]:
                self._fail_locked(key)
            self._active = deque((k, s) for k, s in self._active
                                 if k[0] not in lost)
            self._activate_locked()

    def stats(self) -> dict:
        with self._cv:
            out = {
                "num_pulls": self.num_pulls,
                "bytes_pulled": self.bytes_pulled,
                "num_failed": self.num_failed,
                "queued": len(self._requests),
                "inflight_bytes": self._inflight_bytes,
                "inflight_sources": len(self._infl_kb_rows),
                "device_batches": self.device_batches,
                "oracle_batches": self.oracle_batches,
            }
        # data-path counters from the local plane endpoint (per-transfer
        # MB/s, window occupancy, stripe retries, raw vs pickled bytes)
        plane = getattr(self._cluster, "plane", None)
        if plane is not None:
            out.update(plane.stats())
        return out

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
