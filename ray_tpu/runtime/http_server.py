"""Shared scaffolding for the head's background HTTP endpoints.

The metrics exporter and the dashboard are both stdlib
``ThreadingHTTPServer``s on a daemon thread; this base owns the server
lifecycle and error discipline (handler exceptions answer as JSON 500s
rather than dropping the connection) so the two surfaces cannot drift.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class BackgroundHTTPServer:
    """Subclass and implement ``route(request)``; use ``reply`` to answer.

    ``port=0`` binds an ephemeral port (read it from ``self.port``).
    Non-GET verbs answer 501 unless the subclass widens
    ``allowed_methods``.
    """

    allowed_methods: tuple = ("GET",)

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "http"):
        owner = self

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self):
                if self.command not in owner.allowed_methods:
                    # read-only surfaces (dashboard, metrics) must not
                    # silently treat mutating verbs as GETs
                    self.send_response(501)
                    self.end_headers()
                    return
                try:
                    owner.route(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — 500 + JSON body
                    try:
                        owner.reply(self, json.dumps(
                            {"error": type(e).__name__,
                             "message": str(e)}).encode(),
                            "application/json", status=500)
                    except OSError:
                        pass

            do_GET = _dispatch      # noqa: N815 (stdlib API names)
            do_POST = _dispatch     # noqa: N815
            do_PUT = _dispatch      # noqa: N815
            do_DELETE = _dispatch   # noqa: N815

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"{name}-{self.port}")
        self._thread.start()

    def route(self, request: BaseHTTPRequestHandler) -> None:
        raise NotImplementedError

    @staticmethod
    def reply(request, body: bytes, content_type: str,
              status: int = 200, headers: dict | None = None) -> None:
        request.send_response(status)
        request.send_header("Content-Type", content_type)
        request.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            request.send_header(k, str(v))
        request.end_headers()
        request.wfile.write(body)

    @staticmethod
    def reply_stream(request, chunks, content_type: str,
                     status: int = 200) -> None:
        """Streaming response: chunked transfer for HTTP/1.1 clients,
        close-delimited raw bytes for HTTP/1.0 (which cannot decode
        chunk framing).

        Error discipline: the FIRST chunk is produced before any header
        goes out, so a handler that fails immediately still gets a clean
        500 from the caller's error path.  A failure AFTER headers
        truncates the stream WITHOUT the chunked terminator — the client
        detects the truncation — and is swallowed here (propagating
        would let the dispatcher append a second response to the same
        socket)."""
        it = iter(chunks)
        try:        # producer errors propagate: no headers sent yet,
            first = next(it)        # so the caller's error path 500s
        except StopIteration:
            first = b""
            it = iter(())
        chunked = request.request_version != "HTTP/1.0"
        if chunked:
            request.protocol_version = "HTTP/1.1"
        request.send_response(status)
        request.send_header("Content-Type", content_type)
        if chunked:
            request.send_header("Transfer-Encoding", "chunked")
        request.end_headers()

        def write(chunk: bytes) -> None:
            if not chunk:
                return
            if chunked:
                request.wfile.write(
                    f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
            else:
                request.wfile.write(chunk)
            request.wfile.flush()
        try:
            write(first)
            for chunk in it:
                write(chunk)
            if chunked:
                request.wfile.write(b"0\r\n\r\n")
        except Exception:   # noqa: BLE001 — mid-stream failure: leave
            pass            # the stream visibly truncated (no
            #                 terminator), never a second response
        request.close_connection = True

    @staticmethod
    def not_found(request) -> None:
        request.send_response(404)
        request.end_headers()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
