"""Object recovery: reconstruct lost objects from retained lineage.

Reference parity: the core worker's ``ObjectRecoveryManager`` — when a
plasma object's last copy is lost (node death, eviction), the owner
re-submits the producing task from its pinned lineage, recursively
recovering missing dependencies first; objects with no retained lineage
(puts, exhausted retries, evicted specs) surface ``ObjectLostError``
(``src/ray/core_worker/object_recovery_manager.cc``, SURVEY.md §5.3;
mount empty).
"""

from __future__ import annotations

import threading

from ..common.ids import ObjectID
from ..common.task_spec import TaskType
from .object_ref import ObjectRef
from ..common import clock as _clk


class ObjectRecoveryManager:
    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self.num_reconstructions = 0
        self.num_unrecoverable = 0

    def recover(self, object_id: ObjectID) -> bool:
        """Try to reconstruct ``object_id`` by re-running its producing
        task.  Returns True when a reconstruction is (already) in flight —
        the object will re-seal and waiters wake; False when the object is
        unrecoverable (caller poisons it)."""
        self._await_completion_window(object_id)     # BEFORE the lock:
        # this can wait up to 2s, and holding the manager lock through it
        # would serialize recoveries of unrelated objects behind it
        with self._lock:
            ok = self._recover_locked(object_id)
        if not ok:
            self.num_unrecoverable += 1
        return ok

    def _await_completion_window(self, object_id: ObjectID) -> None:
        """Seal-to-complete window: the producer ALREADY delivered this
        object (seal precedes complete) and the completion is mid-flight
        on a reader thread — nothing in flight will re-seal.  Wait for
        done (normally microseconds) so recovery takes the normal
        retained-lineage path; treating this as "first execution in
        flight" would delete the sealed value and strand every waiter."""
        if object_id.is_put():
            return
        rec = self._cluster.task_manager.get(object_id.task_id())
        if rec is None:
            return
        deadline = _clk.monotonic() + 2.0
        while (not rec.done and self._cluster.store.contains(object_id)
               and _clk.monotonic() < deadline):
            _clk.sleep(0.0005)

    def _recover_locked(self, object_id: ObjectID) -> bool:
        if object_id.is_put():
            return False        # puts have no producing task (reference:
            #                     put objects are not reconstructable)
        tm = self._cluster.task_manager
        rec = tm.get(object_id.task_id())
        if rec is None:         # lineage evicted or unknown owner
            return False
        if rec.spec.task_type is not TaskType.NORMAL_TASK:
            # actor-task outputs need the actor's state replayed — out of
            # scope for lineage reconstruction (reference behaves the same
            # unless the actor itself restarts and replays)
            return False
        if not rec.done:
            # first execution (or an earlier reconstruction) in flight:
            # drop the lost copy's stale entry and wait for its re-seal
            self._drop_entry(object_id)
            return True
        if rec.retries_left <= 0:
            return False
        # recursively recover missing dependencies FIRST: a failed dep
        # makes this object unrecoverable before we claim its record
        store = self._cluster.store
        for a in rec.spec.args:
            if isinstance(a, ObjectRef) and not store.contains(a.id):
                if not self._recover_locked(a.id):
                    return False
        if not tm.mark_reconstructing(rec.spec.task_id):
            return False
        # the lost copy's store entry must go away so gets block until the
        # re-execution seals a fresh value (seal-once: a stale entry would
        # shadow it)
        self._drop_entry(object_id)
        self.num_reconstructions += 1
        self._cluster.head().submit_existing(rec)
        return True

    def _drop_entry(self, object_id: ObjectID) -> None:
        self._cluster.store.delete([object_id])
        self._cluster.directory.drop([object_id])

    def stats(self) -> dict:
        return {"num_reconstructions": self.num_reconstructions,
                "num_unrecoverable": self.num_unrecoverable}
