"""The ``ray_tpu`` command line.

Reference parity: ``ray start --head`` boots the head daemon (GCS +
raylet), ``ray stop`` tears it down, ``ray status/memory/timeline``
introspect, ``ray job submit -- <cmd>`` runs entrypoints on the cluster,
``ray microbenchmark`` is the single-node perf suite from
``python/ray/_private/ray_perf.py`` (BASELINE config #1) — SURVEY.md
§1 layer 15, §4; mount empty.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# NOTE the dash: a directory literally named ray_tpu on a script's
# sys.path[0] (e.g. /tmp) would shadow the real package as an empty
# namespace package
STATE_DIR = "/tmp/ray_tpu-state"
ADDRESS_FILE = f"{STATE_DIR}/ray_current_cluster"


def _write_address(address: str) -> None:
    os.makedirs(os.path.dirname(ADDRESS_FILE), exist_ok=True)
    with open(ADDRESS_FILE, "w") as f:
        f.write(address)


def _resolve_address(explicit: str | None) -> str:
    if explicit:
        return explicit
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(ADDRESS_FILE) as f:
            return f.read().strip()
    except FileNotFoundError:
        raise SystemExit(
            "no running cluster found: pass --address, set "
            "RAY_TPU_ADDRESS, or run `ray_tpu start --head` first")


def _client(address: str | None):
    from ..rpc import transport as _transport
    return _transport.connect(_resolve_address(address))


# -- subcommands -------------------------------------------------------------

def cmd_head(args) -> int:
    """Foreground daemon (what ``start --head`` detaches)."""
    from ..runtime.head import HeadNode
    resources = json.loads(args.resources) if args.resources else None
    head = HeadNode(resources=resources, num_workers=args.num_workers,
                    port=args.port,
                    persist_path=getattr(args, "persist", None))
    _write_address(head.address)
    print(f"ray_tpu head listening on {head.address}", flush=True)
    if head.xlang is not None:
        print(f"cross-language (C++) gateway on {head.xlang.address}",
              flush=True)
    try:
        head.wait_for_shutdown()
    except KeyboardInterrupt:
        head.stop()
    return 0


def cmd_agent(args) -> int:
    """Foreground worker-node agent: joins a head, serves its workers
    (reference: ``ray start --address=<head>`` boots a worker node)."""
    from ..runtime.node_agent import NodeAgent
    resources = json.loads(args.resources) if args.resources else None
    labels = json.loads(args.labels) if args.labels else None
    num_workers = args.num_workers if args.num_workers is not None else 2
    agent = NodeAgent(args.address, resources=resources,
                      num_workers=num_workers, labels=labels,
                      reconnect_timeout_s=getattr(
                          args, "reconnect_timeout", 60.0),
                      standby_address=getattr(
                          args, "standby_address", None))
    print(f"ray_tpu node agent joined {args.address} as node "
          f"{agent.node_id_hex[:16]}… ({num_workers} workers)",
          flush=True)
    try:
        agent.wait_for_shutdown()
    except KeyboardInterrupt:
        agent.stop()
    return 0


def cmd_standby(args) -> int:
    """Foreground hot-standby head: probes the primary, collects agent
    head-down votes, and promotes itself onto the primary's address
    from the shared persist snapshot when the primary dies."""
    from ..runtime.standby import StandbyHead
    resources = json.loads(args.resources) if args.resources else None
    standby = StandbyHead(args.address, port=args.port,
                          persist_path=getattr(args, "persist", None),
                          resources=resources,
                          num_workers=args.num_workers)
    print(f"ray_tpu standby armed at {standby.address}, "
          f"watching {args.address}", flush=True)
    try:
        standby.wait_for_shutdown()
    except KeyboardInterrupt:
        standby.stop()
    return 0


def cmd_start(args) -> int:
    if args.head and args.address:
        raise SystemExit("--head and --address are mutually exclusive")
    if not args.head and args.address:
        if args.block:          # foreground agent (supervisors)
            return cmd_agent(args)
        # detached worker-node agent joining an existing head
        os.makedirs(STATE_DIR, exist_ok=True)
        log_path = os.path.join(STATE_DIR, "agent.log")
        cmd = [sys.executable, "-m", "ray_tpu", "agent",
               "--address", args.address]
        if args.resources:
            cmd += ["--resources", args.resources]
        if args.num_workers is not None:
            cmd += ["--num-workers", str(args.num_workers)]
        if args.labels:
            cmd += ["--labels", args.labels]
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(cmd, stdout=log_f, stderr=log_f,
                                    start_new_session=True)
        print(f"started node agent (pid {proc.pid}) joining "
              f"{args.address}")
        print(f"logs: {log_path}")
        return 0
    if not args.head:
        raise SystemExit("pass --head to start a head, or "
                         "--address=<head> to join one")
    if args.block:
        return cmd_head(args)
    os.makedirs(STATE_DIR, exist_ok=True)
    log_path = os.path.join(STATE_DIR, "head.log")
    cmd = [sys.executable, "-m", "ray_tpu", "head",
           "--port", str(args.port)]
    if args.resources:
        cmd += ["--resources", args.resources]
    if args.num_workers is not None:
        cmd += ["--num-workers", str(args.num_workers)]
    spawn_t = time.time()
    with open(log_path, "ab") as log_f:
        proc = subprocess.Popen(cmd, stdout=log_f, stderr=log_f,
                                start_new_session=True)
    # the daemon writes the address file once its RPC server is up;
    # only a file written AFTER the spawn counts — a stale file from a
    # crashed daemon would hand out a dead address
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if os.path.exists(ADDRESS_FILE) and \
                os.path.getmtime(ADDRESS_FILE) >= spawn_t - 1.0:
            with open(ADDRESS_FILE) as f:
                addr = f.read().strip()
            print(f"started head daemon (pid {proc.pid}) at {addr}")
            print(f"logs: {log_path}")
            print(f'attach with: ray_tpu.init(address="{addr}")')
            return 0
        if proc.poll() is not None:
            raise SystemExit(
                f"head daemon exited rc={proc.returncode}; see {log_path}")
        time.sleep(0.1)
    raise SystemExit("head daemon did not come up within 30s")


def cmd_stop(args) -> int:
    try:
        resolved = _resolve_address(args.address)
    except SystemExit:
        print("no running cluster")
        return 0
    from ..rpc import transport as _transport
    client = _transport.connect(resolved)
    try:
        client.call("stop_daemon", timeout=10.0)
        print("cluster stopping")
    finally:
        client.close()
        # only clear the address file if it records THE cluster we just
        # stopped — `stop --address other:port` must not orphan a
        # still-running local daemon's record
        try:
            with open(ADDRESS_FILE) as f:
                recorded = f.read().strip()
            if recorded == resolved:
                os.unlink(ADDRESS_FILE)
        except FileNotFoundError:
            pass
    return 0


def cmd_status(args) -> int:
    client = _client(args.address)
    try:
        st = client.call("status", timeout=30.0)
    finally:
        client.close()
    print(f"address: {st['address']}  role: {st.get('role', 'primary')}")
    print(f"session: {st['session_dir']}")
    print(f"nodes ({len(st['nodes'])}):")
    for n in st["nodes"]:
        status = n.get("Status", "ALIVE")
        print(f"  {n['NodeID'][:16]}…  row={n['Row']} "
              f"{status:<8} labels={n['Labels']}")
    for d in st.get("drains") or []:
        if d.get("state") == "DRAINING":
            print(f"  draining: {d['node_id'][:16]}… "
                  f"reason={d.get('reason') or '-'} "
                  f"deadline_s={d.get('deadline_s')}")
    health = st.get("health") or {}
    if health.get("num_suspect") or health.get("num_quarantined") \
            or health.get("suspect_rows"):
        print(f"  suspect: rows={health.get('suspect_rows')} "
              f"(loop-lag or quarantine; soft-avoided by the "
              f"scheduler), quarantined={health.get('num_quarantined', 0)}")
    for addr, b in (health.get("breakers") or {}).items():
        print(f"  breaker {addr}: {b['state']} "
              f"failures={b['failures']} opens={b['opens']}")
    ch = st.get("chaos") or {}
    if ch.get("enabled"):
        print(f"chaos: seed={ch['seed']} drop_p={ch['drop_p']} "
              f"dup_p={ch['dup_p']} delay={ch['delay_ms']}ms"
              f"@p={ch['delay_p']} bw={ch['bandwidth_mbps']}MB/s "
              f"partitions={ch['partitions']} "
              f"injected: drop={ch['num_dropped']} "
              f"dup={ch['num_duplicated']} delay={ch['num_delayed']} "
              f"part={ch['num_partitioned']}")
    print("resources:")
    total, avail = st["cluster_resources"], st["available_resources"]
    for name in sorted(total):
        print(f"  {avail.get(name, 0.0):.1f}/{total[name]:.1f} {name}")
    op = st.get("object_plane")
    if op:
        mb = 1 << 20
        print("object plane:")
        print(f"  sent {op['plane_bytes_sent'] / mb:.1f} MB "
              f"(raw {op['plane_raw_bytes_sent'] / mb:.1f} / pickled "
              f"{op['plane_pickled_bytes_sent'] / mb:.1f})  "
              f"received {op['plane_bytes_received'] / mb:.1f} MB "
              f"(raw {op['plane_raw_bytes_received'] / mb:.1f} / pickled "
              f"{op['plane_pickled_bytes_received'] / mb:.1f})")
        print(f"  transfers in={op['plane_transfers_in']} "
              f"failed={op['plane_transfers_failed']} "
              f"stripe_retries={op['plane_stripe_retries']}  "
              f"window now={op['plane_window_occupancy']} "
              f"peak={op['plane_window_peak']}  "
              f"last {op['plane_last_transfer_mbps']} MB/s "
              f"(ewma {op['plane_ewma_transfer_mbps']})")
    pulls = st.get("pulls")
    if pulls:
        print(f"pulls: {pulls['num_pulls']} done "
              f"({pulls['bytes_pulled'] / (1 << 20):.1f} MB), "
              f"{pulls['num_failed']} failed, {pulls['queued']} queued, "
              f"{pulls['inflight_bytes'] / (1 << 20):.1f} MB in flight")
    bc = st.get("broadcasts")
    if bc:
        print(f"broadcasts: {bc['bcast_active_trees']} active, "
              f"{bc['bcast_trees_completed']} done, "
              f"{bc['bcast_trees_failed']} degraded; "
              f"{bc['bcast_members_reached']} replicas via tree "
              f"(+{bc['bcast_members_fallback']} pull fallback, "
              f"{bc['bcast_joins']} pull joins)")
        if bc.get("bcast_trees_started"):
            print(f"  relay fanout {bc['bcast_relay_fanout']}  "
                  f"time-to-all ewma {bc['bcast_time_to_all_ewma_s']}s")
        op2 = st.get("object_plane") or {}
        if op2.get("bcast_chunks_pulled") or \
                op2.get("bcast_chunks_relayed"):
            print(f"  chunks relayed={op2['bcast_chunks_relayed']} "
                  f"pulled={op2['bcast_chunks_pulled']} "
                  f"sealed-served={op2['bcast_chunks_sealed_served']}")
    lz = st.get("leasing") or {}
    if lz.get("sources"):
        print(f"leasing: hit_rate={lz.get('lease_hit_rate', 0.0)} "
              f"local={lz.get('leases_granted_local', 0)} "
              f"spillbacks={lz.get('spillbacks', 0)} "
              f"revocations={lz.get('lease_revocations', 0)} "
              f"issued={lz.get('leases_issued', 0)}")
        sb = lz["sources"].get("standby") or {}
        if sb:
            print(f"  standby: role={sb.get('role')} "
                  f"promotions={sb.get('promotions', 0)} "
                  f"failover_ms={sb.get('failover_ms')}")
    if st["jobs"]:
        print(f"jobs ({len(st['jobs'])}):")
        for j in st["jobs"]:
            print(f"  {j['job_id']}  {j['status']:<10} {j['entrypoint']}")
    plane = st.get("serve") or {}
    if plane:
        print(f"serve deployments ({len(plane)}):")
        for name in sorted(plane):
            s = plane[name]
            line = (f"  {name}  replicas={s.get('replicas', 0)} "
                    f"inflight={s.get('inflight', 0)} "
                    f"queued={s.get('queued', 0)} "
                    f"qps={s.get('qps', 0)} "
                    f"p50={s.get('p50_ms', 0)}ms "
                    f"p99={s.get('p99_ms', 0)}ms "
                    f"shed={s.get('shed', 0)} "
                    f"expired={s.get('expired', 0)}")
            if s.get("batches"):
                line += (f" batches={s['batches']}"
                         f"(mean={s['batch_size_mean']})")
            print(line)
    tr = st.get("train") or {}
    for run in tr.get("runs") or []:
        print(f"train run {run.get('run')}: state={run.get('state')} "
              f"epoch={run.get('epoch')} step={run.get('step')} "
              f"world={run.get('world', 0)} "
              f"goodput_eps={run.get('goodput_eps', 0.0)} "
              f"gang_losses={run.get('gang_losses', 0)} "
              f"planned_resizes={run.get('planned_resizes', 0)} "
              f"failures={run.get('failures', 0)} "
              f"sync_broadcasts={run.get('sync_broadcasts', 0)} "
              f"ckpt_replications={run.get('ckpt_replications', 0)}")
    tl = tr.get("loans") or {}
    if tl.get("loans_total") or tl.get("reverse_lends_total"):
        print(f"capacity loans: serve<-batch "
              f"active={tl.get('loans_active', 0)} "
              f"total={tl.get('loans_total', 0)} "
              f"reclaimed={tl.get('reclaims_total', 0)} "
              f"lost={tl.get('loans_lost', 0)}  |  batch<-serve "
              f"active={tl.get('reverse_lends_active', 0)} "
              f"total={tl.get('reverse_lends_total', 0)} "
              f"returned={tl.get('reverse_lends_returned', 0)} "
              f"lost={tl.get('reverse_lends_lost', 0)}")
    versions = st.get("versions") or {}
    if versions:
        print(f"model versions ({len(versions)}):")
        for name in sorted(versions):
            v = versions[name]
            line = (f"  {name}  current={v.get('current')} "
                    f"previous={v.get('previous') or '—'}")
            ro = v.get("rollout")
            if ro:
                line += (f"  rollout->{ro['to']} {ro['phase']} "
                         f"{ro['flipped']}/{ro['replicas']}")
                if ro.get("error"):
                    line += f" ({ro['error']})"
            print(line)
    return 0


def cmd_drain(args) -> int:
    """``ray_tpu drain <node_id>`` — preemption-notice drain
    (reference: ``ray drain-node`` / the DrainNode RPC)."""
    client = _client(args.address)
    try:
        st = client.call("drain_node", args.node_id, args.reason,
                         args.deadline, timeout=30.0)
    finally:
        client.close()
    print(f"{st['node_id'][:16]}…  {st['state']} "
          f"deadline_s={st['deadline_s']} reason={st['reason']}")
    return 0


def cmd_rollout(args) -> int:
    """``ray_tpu rollout <deployment> [artifact]`` — model-version
    plane.  Without an artifact: print the deployment's KV-journaled
    version record (or every deployment's, with no name).
    ``--pause/--resume/--abort`` write the operator control flag the
    driver-side controller polls between flips — routed through the
    head so the flag lands in the GCS-snapshotted KV and survives a
    standby promotion.  With an artifact path: run the rolling update
    from THIS process; the serve control plane is driver-hosted, so
    starting a rollout only works where the app was deployed (scripts
    embedding ``cli.main`` or an interactive driver) — elsewhere use
    ``ray_tpu.versioning.rollout`` on the driver."""
    op = ("pause" if args.pause else "resume" if args.resume
          else "abort" if args.abort else None)
    if op is not None or args.artifact is None:
        client = _client(args.address)
        try:
            out = client.call("rollout", op or "status",
                              deployment=args.deployment or "",
                              timeout=30.0)
        finally:
            client.close()
        print(json.dumps(out, indent=2, default=str))
        return 0
    if not args.deployment:
        raise SystemExit("rollout start needs a deployment (app) name")
    from .. import versioning
    with open(args.artifact, "rb") as f:
        artifact = f.read()
    summary = versioning.rollout(
        artifact, app_name=args.deployment,
        artifact_label=os.path.basename(args.artifact))
    print(json.dumps(summary, indent=2, default=str))
    return 0 if summary.get("phase") == "SEALED" else 1


def cmd_chaos(args) -> int:
    """``ray_tpu chaos`` — control the head's seeded network-chaos
    plane (``rpc/chaos.py``): inject drops/dups/delays, partition and
    heal links, read the injected-fault trace."""
    if args.off:
        op, kw = "off", {}
    elif args.partition:
        op, kw = "partition", {"src": args.partition[0],
                               "dst": args.partition[1]}
    elif args.heal:
        op, kw = "heal", {"src": args.src, "dst": args.dst}
    elif args.trace:
        op, kw = "trace", {}
    elif args.reset_trace:
        op, kw = "reset_trace", {}
    elif any(v is not None for v in (args.seed, args.drop, args.dup,
                                     args.delay_p, args.delay_ms,
                                     args.bandwidth_mbps)):
        op = "set"
        kw = {"seed": args.seed or 0,
              "drop_p": args.drop or 0.0,
              "dup_p": args.dup or 0.0,
              "delay_p": args.delay_p or 0.0,
              "delay_ms": args.delay_ms or 0.0,
              "bandwidth_mbps": args.bandwidth_mbps or 0.0}
    else:
        op, kw = "status", {}
    # every chaos op is idempotent (set replaces, partition/heal are
    # set ops, status/trace read) — retry so the control plane stays
    # usable against the very fault injection it is steering
    from ..rpc import transport as _transport
    client = _transport.connect(_resolve_address(args.address),
                                retryable=frozenset({"chaos"}))
    try:
        out = client.call("chaos", op, **kw, timeout=30.0)
    finally:
        client.close()
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_list(args) -> int:
    """``ray_tpu list tasks|actors|objects|nodes|placement-groups``
    (reference: the ``ray list`` state CLI)."""
    filters = []
    for f in args.filter or []:
        if "=" not in f:
            raise SystemExit(f"--filter needs key=value, got {f!r}")
        k, v = f.split("=", 1)
        filters.append((k, "=", v))
    client = _client(args.address)
    try:
        rows = client.call("state_list", args.kind, filters or None,
                           timeout=30.0)
    finally:
        client.close()
    if args.format == "json":
        print(json.dumps(rows, indent=2, default=str))
        return 0
    if not rows:
        print(f"no {args.kind}")
        return 0
    columns = list(rows[0])
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in columns}
    print("  ".join(c.ljust(widths[c]) for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c])
                        for c in columns))
    return 0


def cmd_memory(args) -> int:
    client = _client(args.address)
    try:
        stats = client.call("memory", timeout=30.0)
    finally:
        client.close()
    for k, v in sorted(stats.items()):
        print(f"{k}: {v}")
    return 0


def cmd_stacks(args) -> int:
    """Live all-thread stacks of every worker (the reference
    dashboard's py-spy stack sampling — SURVEY.md §5.1(c))."""
    client = _client(args.address)
    try:
        stacks = client.call("worker_stacks", args.node_row, 5.0,
                             timeout=40.0)
    finally:
        client.close()
    if not stacks:
        print("no workers replied")
        return 1
    for key in sorted(stacks):
        print(f"===== worker {key} =====")
        print(stacks[key])
    return 0


def cmd_timeline(args) -> int:
    client = _client(args.address)
    try:
        events = client.call("timeline", timeout=30.0)
    finally:
        client.close()
    out = args.output or f"timeline-{int(time.time())}.json"
    with open(out, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {out}")
    return 0


def cmd_job(args) -> int:
    client = _client(args.address)
    try:
        if args.job_cmd == "submit":
            import shlex
            # shlex.join, not " ".join: args with spaces/quotes (-c
            # "print('x')") must survive the server-side shlex.split
            entrypoint = shlex.join(args.entrypoint)
            runtime_env = json.loads(args.runtime_env_json) \
                if args.runtime_env_json else None
            job_id = client.call("job_submit", entrypoint, runtime_env,
                                 {"submitter": "cli"}, timeout=30.0)
            print(job_id)
            if args.wait:
                while True:
                    st = client.call("job_status", job_id, timeout=30.0)
                    if st["status"] not in ("PENDING", "RUNNING"):
                        print(st["status"])
                        print(client.call("job_logs", job_id,
                                          timeout=30.0), end="")
                        return 0 if st["status"] == "SUCCEEDED" else 1
                    time.sleep(0.25)
        elif args.job_cmd == "status":
            print(json.dumps(client.call("job_status", args.job_id,
                                         timeout=30.0), indent=2))
        elif args.job_cmd == "logs":
            print(client.call("job_logs", args.job_id, timeout=30.0),
                  end="")
        elif args.job_cmd == "list":
            for j in client.call("job_list", timeout=30.0):
                print(f"{j['job_id']}  {j['status']:<10} "
                      f"{j['entrypoint']}")
        elif args.job_cmd == "stop":
            stopped = client.call("job_stop", args.job_id, timeout=30.0)
            print("stopped" if stopped else "already finished")
    finally:
        client.close()
    return 0


def cmd_simulate(args) -> int:
    """``ray_tpu simulate`` — run a scripted chaos campaign on the
    in-process cluster simulator (``ray_tpu/sim/``): N simulated nodes'
    control planes on a virtual clock, faults injected from seeded
    Philox streams, invariants checked after every event.  Same seed ⇒
    identical trace hash; ``--verify-replay`` proves it inline."""
    from ..sim import run_campaign

    def _run(out=None):
        return run_campaign(
            args.nodes, seed=args.seed, campaign=args.campaign,
            faults=args.faults, duration=args.duration,
            autoscale=not args.no_autoscale, out=out,
            progress=lambda msg: print(f"  {msg}", file=sys.stderr))

    result = _run(out=args.out)
    summary = result.to_dict()
    if args.out:
        print(f"trace artifact: {args.out}", file=sys.stderr)
    if args.verify_replay:
        replay = _run()
        summary["replay_hash"] = replay.trace_hash
        summary["replay_matches"] = \
            replay.trace_hash == result.trace_hash
        if not summary["replay_matches"]:
            print(f"replay hash mismatch:\n  first:  "
                  f"{result.trace_hash}\n  replay: "
                  f"{replay.trace_hash}", file=sys.stderr)
            summary["violations"].append(
                "replay hash mismatch: the campaign is not "
                "deterministic")
    if result.violations:
        # every invariant violation self-describes as
        # [inv:<name> @t=<virtual s>]; surface them (and where the
        # trace went) instead of burying them in the JSON blob
        names = ", ".join(sorted(_violation_names(result.violations)))
        print(f"{len(result.violations)} invariant violation(s) — "
              f"names: {names or 'unstructured'}", file=sys.stderr)
        for v in result.violations[:20]:
            print(f"  {v}", file=sys.stderr)
        if len(result.violations) > 20:
            print(f"  ... {len(result.violations) - 20} more",
                  file=sys.stderr)
        print(f"trace artifact: {args.out}" if args.out else
              "re-run with --out PATH to keep the replayable trace",
              file=sys.stderr)
    print(json.dumps(summary, indent=2))
    return 0 if summary.get("ok") and \
        summary.get("replay_matches", True) else 1


def _violation_names(violations):
    from ..sim.invariants import violation_names
    return violation_names(violations)


def cmd_hunt(args) -> int:
    """``ray_tpu hunt`` — coverage-guided adversarial campaign search
    (``ray_tpu/sim/hunt.py``): mutate fault-schedule genomes from the
    campaign archetypes under a seeded Philox stream, chase coverage,
    and ddmin-minimize every invariant violation to a 1-minimal
    replayable genome.  ``--repro ARTIFACT`` replays a committed
    finding under the artifact's own knobs/params and exits 0 iff it
    still reproduces (hash match + signature refires)."""
    from dataclasses import replace as _dc_replace

    from ..sim.cluster import SimParams
    from ..sim.hunt import hunt, load_finding, replay_finding

    if args.repro:
        doc = load_finding(args.repro)
        res, reproduced = replay_finding(doc)
        print(json.dumps({
            "artifact": args.repro,
            "signature": doc["signature"],
            "expected_hash": doc["trace_hash"],
            "replayed_hash": res.trace_hash,
            "hash_matches": res.trace_hash == doc["trace_hash"],
            "violations": res.violations,
            "reproduced": reproduced,
        }, indent=2))
        if reproduced:
            print(f"reproduced: {'+'.join(doc['signature'])} refired, "
                  f"trace hash matched", file=sys.stderr)
            return 0
        print(f"NOT reproduced (bug fixed, or artifact drifted):\n"
              f"  expected {doc['trace_hash']}\n"
              f"  got      {res.trace_hash}", file=sys.stderr)
        return 1

    params = None
    if args.canary:
        params = _dc_replace(SimParams.from_config(), canary=True)
    campaigns = tuple(args.campaigns.split(",")) if args.campaigns \
        else None
    t0 = time.perf_counter()
    r = hunt(
        budget=args.budget, nodes=args.nodes, seed=args.seed,
        faults=args.faults, duration=args.duration,
        campaigns=campaigns, params=params, out_dir=args.out,
        minimize=not args.no_minimize,
        progress=lambda msg: print(f"  {msg}", file=sys.stderr))
    wall = time.perf_counter() - t0
    report = r.to_dict()
    report["wall_s"] = round(wall, 3)
    report["runs_per_sec"] = round(r.runs / max(wall, 1e-9), 2)
    if args.out:
        report_path = os.path.join(args.out, "hunt-report.json")
        os.makedirs(args.out, exist_ok=True)
        with open(report_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
        print(f"hunt report: {report_path}", file=sys.stderr)
    for f in r.findings:
        where = f.artifact or "(re-run with --out DIR for the artifact)"
        print(f"finding {'+'.join(f.signature)}: "
              f"{len(f.genome.ops)} -> {len(f.minimized.ops)} ops "
              f"({f.ddmin_probes} ddmin probes, found after "
              f"{f.found_after_runs} runs) — repro: "
              f"ray_tpu hunt --repro {where}", file=sys.stderr)
    # stdout JSON stays light: full genomes live in the artifacts
    for f in report["findings"]:
        f.pop("genome", None)
        f.pop("minimized", None)
        f.pop("knobs", None)
        f.pop("params", None)
    print(json.dumps(report, indent=2))
    return 0


def cmd_lint(args) -> int:
    """Run rtlint (tools/rtlint) — the project-native concurrency &
    invariant analyzer — over the package.  Exit 0 when every finding
    is baselined; non-zero otherwise, so it can gate PRs."""
    import ray_tpu
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    if not os.path.isdir(os.path.join(repo_root, "tools", "rtlint")):
        print("ray_tpu lint: tools/rtlint not found next to the package "
              f"(looked under {repo_root}); run it from a source checkout",
              file=sys.stderr)
        return 2
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tools.rtlint.__main__ import main as rtlint_main
    forward = []
    if args.format != "text":
        forward.append(f"--format={args.format}")
    if args.update_baseline:
        forward.append("--update-baseline")
    if args.no_baseline:
        forward.append("--no-baseline")
    if args.rules:
        forward.append(f"--rules={args.rules}")
    forward.append(f"--root={repo_root}")
    return rtlint_main(forward)


def cmd_microbenchmark(args) -> int:
    """Single-node perf suite (reference ``ray microbenchmark``,
    BASELINE config #1: many tiny tasks)."""
    import ray_tpu

    ray_tpu.init()
    results = {}
    try:
        @ray_tpu.remote
        def noop():
            return None

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        # warmup
        ray_tpu.get([noop.remote() for _ in range(50)], timeout=60)

        n = args.num_tasks
        t0 = time.perf_counter()
        ray_tpu.get([noop.remote() for _ in range(n)], timeout=300)
        dt = time.perf_counter() - t0
        results["tasks_per_second"] = n / dt

        actor = Counter.remote()
        ray_tpu.get(actor.inc.remote(), timeout=60)
        m = max(n // 4, 100)
        t0 = time.perf_counter()
        ray_tpu.get([actor.inc.remote() for _ in range(m)], timeout=300)
        dt = time.perf_counter() - t0
        results["actor_calls_per_second"] = m / dt

        t0 = time.perf_counter()
        for _ in range(100):
            ray_tpu.get(ray_tpu.put(b"x" * 1024), timeout=60)
        results["put_get_p50_us"] = (time.perf_counter() - t0) / 100 * 1e6
    finally:
        ray_tpu.shutdown()
    for k, v in results.items():
        print(f"{k}: {v:,.1f}")
    print(json.dumps({"microbenchmark": results}))
    return 0


# -- parser ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    ph = sub.add_parser("head", help="run the head daemon in foreground")
    ph.add_argument("--port", type=int, default=0)
    ph.add_argument("--resources", default=None)
    ph.add_argument("--num-workers", type=int, default=None)
    ph.add_argument("--persist", default=None,
                    help="GCS snapshot path: enables head fault "
                         "tolerance (restore on restart)")
    ph.set_defaults(fn=cmd_head)

    ps = sub.add_parser("start", help="start cluster daemons")
    ps.add_argument("--head", action="store_true")
    ps.add_argument("--address", default=None,
                    help="join an existing head as a worker node "
                         "(mutually exclusive with --head)")
    ps.add_argument("--port", type=int, default=0)
    ps.add_argument("--resources", default=None,
                    help='JSON, e.g. \'{"CPU": 8, "memory": 16}\'')
    ps.add_argument("--num-workers", type=int, default=None)
    ps.add_argument("--labels", default=None,
                    help="JSON node labels (worker nodes only)")
    ps.add_argument("--block", action="store_true",
                    help="run in the foreground")
    ps.set_defaults(fn=cmd_start)

    pa = sub.add_parser("agent",
                        help="run a worker-node agent in foreground")
    pa.add_argument("--address", required=True,
                    help="head RPC address (host:port)")
    pa.add_argument("--resources", default=None)
    pa.add_argument("--num-workers", type=int, default=2)
    pa.add_argument("--labels", default=None, help="JSON node labels")
    pa.add_argument("--standby-address", default=None,
                    help="hot-standby head to vote at on head-link "
                         "loss (sub-heartbeat failover)")
    pa.add_argument("--reconnect-timeout", type=float, default=60.0,
                    help="seconds to retry a lost head before exiting "
                         "(0 disables; survives head restarts)")
    pa.set_defaults(fn=cmd_agent)

    psb = sub.add_parser(
        "standby",
        help="run a hot-standby head watching a primary")
    psb.add_argument("--address", required=True,
                     help="primary head host:port to watch")
    psb.add_argument("--port", type=int, default=0,
                     help="standby vote/status port (0 = ephemeral)")
    psb.add_argument("--persist", default=None,
                     help="the PRIMARY's persist snapshot path; the "
                          "promoted head restores from it")
    psb.add_argument("--resources", default=None)
    psb.add_argument("--num-workers", type=int, default=None)
    psb.set_defaults(fn=cmd_standby)

    pst = sub.add_parser("stop", help="stop the running cluster")
    pst.add_argument("--address", default=None)
    pst.set_defaults(fn=cmd_stop)

    pq = sub.add_parser("status", help="cluster status")
    pq.add_argument("--address", default=None)
    pq.set_defaults(fn=cmd_status)

    pd = sub.add_parser(
        "drain", help="gracefully retire a node (ALIVE -> DRAINING "
                      "-> removed); running tasks finish, queued work "
                      "and bundles re-place elsewhere")
    pd.add_argument("node_id", help="node id (hex, from `status`)")
    pd.add_argument("--reason", default="cli drain")
    pd.add_argument("--deadline", type=float, default=None,
                    help="grace seconds before force-removal "
                         "(default: drain_deadline_s config)")
    pd.add_argument("--address", default=None)
    pd.set_defaults(fn=cmd_drain)

    pr = sub.add_parser(
        "rollout", help="model-version plane: show the version "
        "journal, pause/resume/abort an in-flight rolling update, or "
        "run one (driver-hosted: start only works where the app runs)")
    pr.add_argument("deployment", nargs="?", default="",
                    help="serve app name (omit to list every "
                         "deployment's version record)")
    pr.add_argument("artifact", nargs="?", default=None,
                    help="path to the new weight artifact — starts a "
                         "rolling update and blocks until SEALED or "
                         "ROLLED_BACK")
    pr.add_argument("--pause", action="store_true",
                    help="hold the flip loop after the current replica")
    pr.add_argument("--resume", action="store_true",
                    help="release a paused rollout")
    pr.add_argument("--abort", action="store_true",
                    help="stop flipping and roll back to the old "
                         "version")
    pr.add_argument("--address", default=None)
    pr.set_defaults(fn=cmd_rollout)

    pc = sub.add_parser(
        "chaos", help="control the seeded network-chaos plane "
        "(drop/dup/delay injection, partitions, fault trace)")
    pc.add_argument("--address", default=None)
    pc.add_argument("--seed", type=int, default=None)
    pc.add_argument("--drop", type=float, default=None,
                    help="per-message drop probability")
    pc.add_argument("--dup", type=float, default=None,
                    help="per-message duplication probability")
    pc.add_argument("--delay-p", type=float, default=None,
                    help="per-message delay probability")
    pc.add_argument("--delay-ms", type=float, default=None,
                    help="mean injected delay (ms)")
    pc.add_argument("--bandwidth-mbps", type=float, default=None,
                    help="per-connection bandwidth cap (MB/s)")
    pc.add_argument("--partition", nargs=2, metavar=("SRC", "DST"),
                    default=None,
                    help="add a directed partition SRC ↛ DST "
                    "('*' = wildcard)")
    pc.add_argument("--heal", action="store_true",
                    help="remove partitions (all, or --src/--dst)")
    pc.add_argument("--src", default=None)
    pc.add_argument("--dst", default=None)
    pc.add_argument("--status", action="store_true")
    pc.add_argument("--trace", action="store_true",
                    help="dump the injected-fault trace")
    pc.add_argument("--reset-trace", action="store_true",
                    help="clear streams+trace (replay from draw 0)")
    pc.add_argument("--off", action="store_true")
    pc.set_defaults(fn=cmd_chaos)

    pl = sub.add_parser("list", help="list live cluster state")
    pl.add_argument("kind", choices=["tasks", "actors", "objects",
                                     "nodes", "placement-groups"])
    pl.add_argument("--filter", action="append", default=None,
                    metavar="KEY=VALUE",
                    help="equality filter, repeatable")
    pl.add_argument("--format", choices=["table", "json"],
                    default="table")
    pl.add_argument("--address", default=None)
    pl.set_defaults(fn=cmd_list)

    pm = sub.add_parser("memory", help="object store stats")
    pm.add_argument("--address", default=None)
    pm.set_defaults(fn=cmd_memory)

    pt = sub.add_parser("timeline", help="dump Chrome trace events")
    pt.add_argument("--address", default=None)
    pt.add_argument("-o", "--output", default=None)
    pt.set_defaults(fn=cmd_timeline)

    ps2 = sub.add_parser("stacks",
                         help="live worker stack dump (py-spy analogue)")
    ps2.add_argument("--address", default=None)
    ps2.add_argument("--node-row", type=int, default=None)
    ps2.set_defaults(fn=cmd_stacks)

    pj = sub.add_parser("job", help="job submission")
    pj.add_argument("--address", default=None)
    jsub = pj.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--runtime-env-json", default=None)
    js.add_argument("--wait", action="store_true",
                    help="block until the job finishes; exit 1 on failure")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    for name in ("status", "logs", "stop"):
        jx = jsub.add_parser(name)
        jx.add_argument("job_id")
    jsub.add_parser("list")
    pj.set_defaults(fn=cmd_job)

    pb = sub.add_parser("microbenchmark",
                        help="single-node perf suite")
    pb.add_argument("--num-tasks", type=int, default=2000)
    pb.set_defaults(fn=cmd_microbenchmark)

    from ..sim.campaign import CAMPAIGNS as _campaigns
    psim = sub.add_parser(
        "simulate",
        help="run a chaos campaign on the in-process cluster simulator "
             "(virtual clock, seeded faults, invariant checks after "
             "every event); same seed reproduces the identical trace "
             "hash")
    psim.add_argument("--nodes", type=int, default=1000,
                      help="simulated cluster size (default 1000)")
    psim.add_argument("--seed", type=int, default=0,
                      help="Philox seed: keys the job load, the fault "
                           "schedule and every chaos link stream")
    psim.add_argument("--campaign", choices=_campaigns, default="mixed")
    psim.add_argument("--faults", type=int, default=50,
                      help="scheduled fault draws (heals/restarts ride "
                           "along; default 50)")
    psim.add_argument("--duration", type=float, default=None,
                      help="virtual seconds of chaos before quiesce "
                           "(default max(180, 4*faults))")
    psim.add_argument("--out", default=None, metavar="PATH",
                      help="write the replayable trace artifact "
                           "(ray_tpu-sim-trace/1 JSON)")
    psim.add_argument("--verify-replay", action="store_true",
                      help="run the campaign twice and fail unless the "
                           "trace hashes match")
    psim.add_argument("--no-autoscale", action="store_true",
                      help="disable the simulated autoscaler loop")
    psim.set_defaults(fn=cmd_simulate)

    phunt = sub.add_parser(
        "hunt",
        help="coverage-guided adversarial chaos search: mutate fault "
             "schedules, hunt invariant violations, ddmin each failure "
             "to a minimal replayable genome")
    phunt.add_argument("--budget", type=int, default=120,
                       help="exploration sim runs to spend "
                            "(ddmin probes ride on top; default 120)")
    phunt.add_argument("--nodes", type=int, default=24,
                       help="simulated cluster size per run "
                            "(default 24)")
    phunt.add_argument("--seed", type=int, default=0,
                       help="Philox seed for the whole search: same "
                            "(seed, budget) finds the same failures "
                            "in the same order")
    phunt.add_argument("--faults", type=int, default=24,
                       help="fault draws per seed genome (default 24)")
    phunt.add_argument("--duration", type=float, default=160.0,
                       help="virtual seconds of chaos per run "
                            "(default 160)")
    phunt.add_argument("--campaigns", default=None,
                       help="comma-separated archetype seed genomes "
                            "(default: all campaigns)")
    phunt.add_argument("--out", default=None, metavar="DIR",
                       help="write finding artifacts "
                            "(ray_tpu-hunt-finding/1) and the hunt "
                            "report here")
    phunt.add_argument("--repro", default=None, metavar="ARTIFACT",
                       help="replay a finding artifact under its own "
                            "knobs/params; exit 0 iff it reproduces")
    phunt.add_argument("--canary", action="store_true",
                       help="arm the planted canary bug (smoke-tests "
                            "the search itself)")
    phunt.add_argument("--no-minimize", action="store_true",
                       help="skip ddmin on findings")
    phunt.set_defaults(fn=cmd_hunt)

    plint = sub.add_parser(
        "lint",
        help="concurrency & invariant analyzer (rtlint): blocking-"
             "under-lock, lock-order cycles, config-knob discipline, "
             "thread lifecycle, lockset races, replay determinism; "
             "non-zero exit on non-baselined findings")
    plint.add_argument("--format", choices=("text", "json", "sarif"),
                       default="text")
    plint.add_argument("--rules", default=None,
                       help="comma-separated subset of "
                            "W1,W2,W3,W4,W5,W6,W7,W8")
    plint.add_argument("--update-baseline", action="store_true",
                       help="accept current findings into "
                            "tools/rtlint/baseline.json")
    plint.add_argument("--no-baseline", action="store_true",
                       help="report every finding, ignore the baseline")
    plint.set_defaults(fn=cmd_lint)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "entrypoint", None) and args.entrypoint \
            and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
