"""CLI entry points (``python -m ray_tpu`` / console script).

Reference parity: ``python/ray/scripts/scripts.py`` — ``ray start/stop/
status/memory/timeline/microbenchmark`` and ``ray job submit/status/
logs/list/stop`` (SURVEY.md §1 layer 15; mount empty).
"""

from .cli import main

__all__ = ["main"]
