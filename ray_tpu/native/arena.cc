// Shared-memory arena allocator — the plasma-store analogue's native core.
//
// Reference parity: upstream's plasma store manages mmap arenas with an
// allocator + eviction inside the raylet process, and clients map the same
// memory for zero-copy reads (src/ray/object_manager/plasma/ — SURVEY.md
// §2.1 plasma row; mount empty).  Here the arena lives in one /dev/shm
// file: the owning raylet process allocates/frees via this allocator;
// worker processes map the file read-only and read sealed objects
// zero-copy.  Python owns object metadata (id -> offset/size); this layer
// is ONLY the allocator, kept native for speed and for process-shared
// locking (pthread robust mutex in the mapped header).
//
// Layout:  [Header][Block hdr][payload][Block hdr][payload]...
// Free policy: first-fit with block splitting; forward coalescing on free
// (freeing neighbors merges right-adjacent runs; no boundary tags).

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

extern "C" {

struct Header {
  uint64_t magic;
  uint64_t capacity;       // total mapped bytes, including this header
  uint64_t data_start;     // offset of the first block header
  uint64_t bytes_in_use;   // sum of allocated payload capacities
  pthread_mutex_t lock;    // process-shared, robust
};

struct Block {
  uint64_t size;   // payload capacity (aligned)
  uint64_t free_;  // 1 = free
};

static const uint64_t kMagic = 0x52415954505541ULL;  // "RAYTPUA"
static const uint64_t kAlign = 64;                   // cache-line payloads

static inline uint64_t align_up(uint64_t x) {
  return (x + kAlign - 1) & ~(kAlign - 1);
}

static void lock_arena(Header* h) {
  int rc = pthread_mutex_lock(&h->lock);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->lock);
}

int arena_init(uint8_t* base, uint64_t capacity) {
  if (capacity < 4096) return -1;
  Header* h = (Header*)base;
  h->magic = kMagic;
  h->capacity = capacity;
  h->data_start = align_up(sizeof(Header));
  h->bytes_in_use = 0;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&h->lock, &attr) != 0) return -1;
  Block* b = (Block*)(base + h->data_start);
  b->size = capacity - h->data_start - sizeof(Block);
  b->free_ = 1;
  return 0;
}

int arena_check(uint8_t* base) {
  return ((Header*)base)->magic == kMagic ? 0 : -1;
}

// Returns the PAYLOAD offset (never 0), or 0 when no block fits.
uint64_t arena_alloc(uint8_t* base, uint64_t size) {
  Header* h = (Header*)base;
  uint64_t need = align_up(size ? size : 1);
  lock_arena(h);
  uint64_t off = h->data_start;
  while (off + sizeof(Block) <= h->capacity) {
    Block* b = (Block*)(base + off);
    if (b->free_ && b->size >= need) {
      uint64_t leftover = b->size - need;
      if (leftover > sizeof(Block) + kAlign) {  // split
        Block* nb = (Block*)(base + off + sizeof(Block) + need);
        nb->size = leftover - sizeof(Block);
        nb->free_ = 1;
        b->size = need;
      }
      b->free_ = 0;
      h->bytes_in_use += b->size;
      pthread_mutex_unlock(&h->lock);
      return off + sizeof(Block);
    }
    off += sizeof(Block) + b->size;
  }
  pthread_mutex_unlock(&h->lock);
  return 0;
}

// payload_off must be a value returned by arena_alloc and not yet freed.
int arena_free(uint8_t* base, uint64_t payload_off) {
  Header* h = (Header*)base;
  if (payload_off < h->data_start + sizeof(Block) ||
      payload_off >= h->capacity)
    return -1;
  lock_arena(h);
  uint64_t off = payload_off - sizeof(Block);
  Block* b = (Block*)(base + off);
  if (b->free_) {
    pthread_mutex_unlock(&h->lock);
    return -1;  // double free
  }
  b->free_ = 1;
  h->bytes_in_use -= b->size;
  // forward coalesce
  uint64_t next_off = off + sizeof(Block) + b->size;
  while (next_off + sizeof(Block) <= h->capacity) {
    Block* nb = (Block*)(base + next_off);
    if (!nb->free_) break;
    b->size += sizeof(Block) + nb->size;
    next_off = off + sizeof(Block) + b->size;
  }
  pthread_mutex_unlock(&h->lock);
  return 0;
}

// Touch one byte per page of [offset, offset+size) so first-touch
// faults (tmpfs page allocation + zeroing) are paid here instead of
// inside a landing memcpy.  Reads only — safe concurrent with writers
// to the same range — and called WITHOUT the arena lock: ctypes drops
// the GIL for the call, so a transfer can warm its ingest block on a
// spare core while chunk bytes are in flight.
uint64_t arena_touch(uint8_t* base, uint64_t offset, uint64_t size) {
  const uint64_t kPage = 4096;
  volatile uint8_t acc = 0;
  uint64_t end = offset + size;
  for (uint64_t off = offset; off < end; off += kPage) acc += base[off];
  return acc;
}

uint64_t arena_bytes_in_use(uint8_t* base) {
  return ((Header*)base)->bytes_in_use;
}

uint64_t arena_capacity(uint8_t* base) {
  return ((Header*)base)->capacity;
}

// Largest free payload currently allocatable (for spill decisions).
uint64_t arena_largest_free(uint8_t* base) {
  Header* h = (Header*)base;
  lock_arena(h);
  uint64_t best = 0;
  uint64_t off = h->data_start;
  while (off + sizeof(Block) <= h->capacity) {
    Block* b = (Block*)(base + off);
    if (b->free_ && b->size > best) best = b->size;
    off += sizeof(Block) + b->size;
  }
  pthread_mutex_unlock(&h->lock);
  return best;
}

}  // extern "C"
