"""Native components: build-on-first-use C++ with ctypes bindings.

Reference parity: the reference's hot runtime pieces are C++ (plasma store
allocator, raylet event loop — SURVEY.md §2.1); here the shared-memory
arena allocator is native (``arena.cc``) and Python binds it with ctypes
(pybind11 is not in this image).  The library is compiled once per source
change with the baked-in g++ and cached next to the source.
"""

from __future__ import annotations

import ctypes
import hashlib
import mmap
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "arena.cc")
_build_lock = threading.Lock()
_lib_handle = None


def _lib_path() -> str:
    """Cache key is a CONTENT hash of the source, embedded in the library
    filename: mtimes are not preserved by git checkouts, so an mtime test
    could silently load a stale binary with a mismatched shared-memory
    layout.  Build artifacts are never committed (.gitignore *.so)."""
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_HERE, f"_libarena_{digest}.so")


def _ensure_built() -> str:
    """Compile arena.cc -> _libarena_<srchash>.so if not already cached."""
    lib = _lib_path()
    if os.path.exists(lib):
        return lib
    with _build_lock:
        if os.path.exists(lib):
            return lib
        tmp = lib + f".tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-pthread",
               "-o", tmp, _SRC]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
        os.replace(tmp, lib)
        # older-revision caches are left in place: a concurrent process
        # may be between building and dlopening one (they are a few KB
        # and gitignored, so accumulation is harmless)
    return lib


def _lib():
    global _lib_handle
    if _lib_handle is None:
        lib = ctypes.CDLL(_ensure_built())
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.arena_init.argtypes = [u8p, ctypes.c_uint64]
        lib.arena_init.restype = ctypes.c_int
        lib.arena_check.argtypes = [u8p]
        lib.arena_check.restype = ctypes.c_int
        lib.arena_alloc.argtypes = [u8p, ctypes.c_uint64]
        lib.arena_alloc.restype = ctypes.c_uint64
        lib.arena_free.argtypes = [u8p, ctypes.c_uint64]
        lib.arena_free.restype = ctypes.c_int
        for fn in ("arena_bytes_in_use", "arena_capacity",
                   "arena_largest_free"):
            getattr(lib, fn).argtypes = [u8p]
            getattr(lib, fn).restype = ctypes.c_uint64
        lib.arena_touch.argtypes = [u8p, ctypes.c_uint64,
                                    ctypes.c_uint64]
        lib.arena_touch.restype = ctypes.c_uint64
        _lib_handle = lib
    return _lib_handle


class ArenaFullError(MemoryError):
    """No free block large enough (caller should spill/evict and retry)."""


class Arena:
    """One mmap'd shared-memory arena.

    The OWNER (raylet/driver process) creates it read-write and is the only
    process that allocates, writes, and frees.  READERS (workers) attach
    read-only and get zero-copy memoryviews of sealed payloads.
    """

    def __init__(self, path: str, capacity: int | None = None, *,
                 create: bool = False):
        self.path = path
        self._owner = create
        if create:
            assert capacity is not None
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, capacity)
                self._mm = mmap.mmap(fd, capacity)
            finally:
                os.close(fd)
            self._base = (ctypes.c_uint8 * capacity).from_buffer(self._mm)
            rc = _lib().arena_init(self._base, capacity)
            if rc != 0:
                raise RuntimeError("arena_init failed")
        else:
            fd = os.open(path, os.O_RDONLY)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
            finally:
                os.close(fd)
            self._base = None           # readers never call the allocator
        self._view = memoryview(self._mm)

    # -- owner-side ---------------------------------------------------------
    def alloc(self, size: int) -> int:
        off = _lib().arena_alloc(self._base, size)
        if off == 0:
            raise ArenaFullError(f"arena cannot fit {size} bytes")
        return int(off)

    def free(self, offset: int) -> None:
        rc = _lib().arena_free(self._base, offset)
        if rc != 0:
            raise ValueError(f"bad arena free at offset {offset}")

    def put(self, data) -> tuple[int, int]:
        """Allocate + copy + seal in one step; returns (offset, size)."""
        data = memoryview(data)
        n = data.nbytes
        off = self.alloc(n)
        self._view[off:off + n] = data
        return off, n

    def write(self, offset: int, data) -> None:
        data = memoryview(data)
        self._view[offset:offset + data.nbytes] = data

    def touch(self, offset: int, size: int) -> None:
        """Pre-fault [offset, offset+size): one read per page, native
        and GIL-free (ctypes releases the GIL for the call), so a
        transfer can warm its landing block on a spare core while the
        bytes are still on the wire."""
        _lib().arena_touch(self._base, offset, size)

    def bytes_in_use(self) -> int:
        return int(_lib().arena_bytes_in_use(self._base))

    def capacity(self) -> int:
        return int(_lib().arena_capacity(self._base))

    def largest_free(self) -> int:
        return int(_lib().arena_largest_free(self._base))

    # -- both sides ---------------------------------------------------------
    def view(self, offset: int, size: int) -> memoryview:
        """Zero-copy view of a sealed payload."""
        return self._view[offset:offset + size]

    def close(self) -> None:
        try:
            self._view.release()
        except Exception:
            pass
        # the ctypes array holds a buffer export on the mmap; drop it first
        self._base = None
        try:
            self._mm.close()
        except (BufferError, Exception):
            pass
        if self._owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass
