// Concurrency stress harness for the arena allocator, built under
// ASAN/TSAN (see Makefile).
//
// Reference parity: upstream runs its C++ core under --config=asan /
// --config=tsan bazel CI jobs (SURVEY.md §4 sanitizers row, §5.2);
// this is the equivalent discipline for the one native component here.
//
// The arena's contract: ONE owner process allocates/frees (possibly
// from several threads — raylet reader threads free pins concurrently
// with scheduler-thread allocs) while the process-shared robust mutex
// serializes mutation.  The stress spawns N threads doing random
// alloc/write/verify/free cycles over a small arena (high contention +
// frequent exhaustion), then checks zero corruption, zero leaked
// bytes, and an intact header.  (Full single-run coalescing is NOT
// asserted: arena.cc coalesces forward-only, so a drained arena may
// legitimately end as several free runs.)

#include <pthread.h>
#include <sched.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

extern "C" {
int arena_init(uint8_t* base, uint64_t capacity);
int arena_check(uint8_t* base);
uint64_t arena_alloc(uint8_t* base, uint64_t size);  // 0 = exhausted
int arena_free(uint8_t* base, uint64_t payload_off);
uint64_t arena_bytes_in_use(uint8_t* base);
}

static const int kThreads = 8;
static const int kIters = 20000;
static const uint64_t kCapacity = 1 << 20;  // 1 MB: constant pressure

struct Ctx {
  uint8_t* base;
  unsigned seed;
  long allocs = 0, fails = 0, corrupt = 0;
};

static void* worker(void* arg) {
  Ctx* ctx = static_cast<Ctx*>(arg);
  const int kHold = 48;                 // blocks held concurrently
  uint64_t offs[kHold];
  uint64_t sizes[kHold];
  int held = 0;
  for (int i = 0; i < kIters; i++) {
    bool do_alloc = held == 0 ||
        (held < kHold && (rand_r(&ctx->seed) & 1));
    if (do_alloc) {
      uint64_t size = 64 + rand_r(&ctx->seed) % 16384;
      uint64_t off = arena_alloc(ctx->base, size);
      if (off == 0) {
        ctx->fails++;       // exhaustion under contention is expected
        continue;
      }
      ctx->allocs++;
      memset(ctx->base + off, (unsigned char)((off ^ size) | 1), size);
      offs[held] = off;
      sizes[held] = size;
      held++;
      if ((i & 15) == 0) sched_yield();
    } else {
      int pick = rand_r(&ctx->seed) % held;
      uint64_t off = offs[pick], size = sizes[pick];
      unsigned char tag = (unsigned char)((off ^ size) | 1);
      for (uint64_t j = 0; j < size; j += 257) {
        if (ctx->base[off + j] != tag) {
          ctx->corrupt++;   // another thread's block overlapped ours
          break;
        }
      }
      if (arena_free(ctx->base, off) != 0) ctx->corrupt++;
      offs[pick] = offs[held - 1];
      sizes[pick] = sizes[held - 1];
      held--;
    }
  }
  while (held > 0) {                    // drain: leak check must be 0
    held--;
    if (arena_free(ctx->base, offs[held]) != 0) ctx->corrupt++;
  }
  return nullptr;
}

int main() {
  uint8_t* base = static_cast<uint8_t*>(aligned_alloc(64, kCapacity));
  if (base == nullptr) {
    fprintf(stderr, "aligned_alloc failed (environment, not arena)\n");
    return 2;
  }
  if (arena_init(base, kCapacity) != 0) {
    fprintf(stderr, "arena_init failed\n");
    return 2;
  }
  pthread_t threads[kThreads];
  Ctx ctxs[kThreads];
  for (int t = 0; t < kThreads; t++) {
    ctxs[t].base = base;
    ctxs[t].seed = 1234u + t;
    pthread_create(&threads[t], nullptr, worker, &ctxs[t]);
  }
  long allocs = 0, fails = 0, corrupt = 0;
  for (int t = 0; t < kThreads; t++) {
    pthread_join(threads[t], nullptr);
    allocs += ctxs[t].allocs;
    fails += ctxs[t].fails;
    corrupt += ctxs[t].corrupt;
  }
  uint64_t leaked = arena_bytes_in_use(base);
  int magic_ok = arena_check(base);
  free(base);
  printf("allocs=%ld exhaustions=%ld corruptions=%ld leaked=%llu\n",
         allocs, fails, corrupt, (unsigned long long)leaked);
  if (corrupt != 0 || leaked != 0 || magic_ok != 0) {
    fprintf(stderr, "STRESS FAILED\n");
    return 1;
  }
  printf("ARENA STRESS PASSED\n");
  return 0;
}
