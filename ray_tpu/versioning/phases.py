"""Rollout state-machine phases, shared by the live controller
(``versioning/rollout.py``) and the simulator twin (``sim/rollout.py``)
so trace artifacts, invariants, and observability all speak one
vocabulary."""

STAGING = "STAGING"             # new version allocated, artifact pinned
BROADCASTING = "BROADCASTING"   # weights streaming 1->N down the tree
FLIPPING = "FLIPPING"           # replicas flipping one-at-a-time
SEALED = "SEALED"               # every replica on the new version
ROLLED_BACK = "ROLLED_BACK"     # failure: re-flipped to the old version
PAUSED = "PAUSED"               # operator hold between flips

TERMINAL = (SEALED, ROLLED_BACK)

# legal transitions; the registry refuses anything else so a buggy
# driver cannot journal an impossible history
NEXT = {
    STAGING: (BROADCASTING, ROLLED_BACK),
    BROADCASTING: (FLIPPING, ROLLED_BACK),
    FLIPPING: (PAUSED, SEALED, ROLLED_BACK),
    PAUSED: (FLIPPING, ROLLED_BACK),
    SEALED: (),
    ROLLED_BACK: (),
}
