"""Live rollout controller: roll a new weight version across a running
deployment with zero accepted-request loss.

Driver-hosted, like the rest of the serve control plane (the
``_Controller`` actors and router registry live in the driver
process); every phase transition is journaled through
:class:`~ray_tpu.versioning.registry.VersionRegistry` into the
GCS-snapshotted KV, so the head, the CLI and the dashboard observe the
rollout — and a standby promotion inherits the journal.

Flip discipline per replica (the retire-loaner two-step, generalized):

1. ``begin_flip`` — the controller pulls the replica out of the
   routing set (version bump: shards stop dispatching to it) but keeps
   it alive to finish in-flight work.
2. drain — poll the replica shell's live call count to zero, bounded
   by ``rollout_flip_drain_timeout_s`` (the cap is at most
   ``max_ongoing_requests`` calls deep).
3. ``_reload`` — swap weights (broadcast-staged ObjectRef resolves to
   a replica-local copy) and run the verification probe.
4. ``commit_flip`` — re-enter routing under the new version tag; or
   ``cancel_flip`` back to the old tag on probe failure, which trips a
   rollback of every replica already flipped.

Failure trips: verification-probe failure, replica death mid-flip
(tolerated — the dead replica simply leaves the set; the rollout
continues) and an SLO regression (the deployment's p99/latency EWMA
exceeding ``rollout_slo_factor`` x the pre-rollout baseline).
"""

from __future__ import annotations

import threading

from ..common import clock as _clk
from ..common.config import get_config
from . import phases
from .registry import VersionRegistry

__all__ = ["RolloutController", "rollout", "rollout_status",
           "pause_rollout", "resume_rollout", "abort_rollout"]

# retained artifact refs (weights kept for rollback until seal trims
# them): (deployment, version) -> ObjectRef.  Driver-process registry,
# like serve's _apps table.
_ARTIFACTS: dict = {}
_ARTIFACTS_LOCK = threading.Lock()


def _retain(deployment: str, version: str, ref) -> None:
    with _ARTIFACTS_LOCK:
        _ARTIFACTS[(deployment, version)] = ref


def _retained(deployment: str, version: str):
    with _ARTIFACTS_LOCK:
        return _ARTIFACTS.get((deployment, version))


def _trim_retained(deployment: str, keep: list[str]) -> None:
    with _ARTIFACTS_LOCK:
        for key in [k for k in _ARTIFACTS
                    if k[0] == deployment and k[1] not in keep]:
            _ARTIFACTS.pop(key, None)


class RolloutController:
    """One rollout of ``artifact`` over the app named ``app_name``."""

    def __init__(self, artifact: bytes, app_name: str = "default",
                 artifact_label: str = "", probe=None):
        self._artifact = artifact
        self._app_name = app_name
        self._label = artifact_label or f"artifact-{len(artifact)}B"
        self._probe = probe
        self._registry = VersionRegistry()
        self._flipped: list[str] = []   # key_hex, flip order
        self.max_flip_downtime_s = 0.0

    # -- plumbing ------------------------------------------------------------
    def _running(self):
        from ..serve.deployment import _apps, _apps_lock
        with _apps_lock:
            running = _apps.get(self._app_name)
        if running is None:
            raise KeyError(f"no running serve app {self._app_name!r}")
        return running

    @staticmethod
    def _key(handle) -> str:
        return handle._actor_id.binary().hex()

    def _lat_ewma(self, kv_base: str) -> float:
        from ..experimental.internal_kv import _internal_kv_get
        raw = _internal_kv_get(f"lat-{kv_base}".encode(),
                               namespace="serve")
        try:
            return float(raw) if raw else 0.0
        except ValueError:
            return 0.0

    def _control(self, dep: str) -> str:
        return self._registry.control(dep)

    # -- the state machine ---------------------------------------------------
    def run(self) -> dict:
        import ray_tpu
        from ..api import _get_runtime

        cfg = get_config()
        running = self._running()
        ctl = running.controller
        dep = running.deployment.name
        reg = self._registry
        t0 = _clk.monotonic()

        rec = reg.stage(dep, self._label)
        ro = rec["rollout"]
        old, new = ro["from"], ro["to"]

        # STAGING: pin the new weights in the object store
        ref = ray_tpu.put(self._artifact)
        _retain(dep, new, ref)

        # BROADCASTING: stream 1->N down the bandwidth-derated tree
        # while the old version keeps serving.  Degradation (a member
        # falling back to a striped pull) is not failure — _reload's
        # get() resolves from the nearest replica either way.
        reg.set_phase(dep, phases.BROADCASTING)
        try:
            summary = _get_runtime().cluster.broadcasts.broadcast(ref)
            reg.set_phase(dep, phases.BROADCASTING,
                          broadcast=summary.get("reached", 0))
        except Exception:   # noqa: BLE001 — single-node/test topology
            pass

        _ver, reps, _kv_key, info = ray_tpu.get(
            ctl.get_replicas.remote(), timeout=60)
        n_loaners = int(info.get("loaners", 0))
        targets = reps[:len(reps) - n_loaners] if n_loaners else reps
        baseline_lat = self._lat_ewma(info["base"])
        reg.set_phase(dep, phases.FLIPPING, replicas=len(targets))
        ray_tpu.get(ctl.set_rollout_active.remote(True), timeout=30)

        error = ""
        try:
            for i, handle in enumerate(targets):
                hold = self._hold_for_operator(dep)
                if hold == "abort":
                    error = "aborted by operator"
                    break
                if not self._flip_one(ctl, handle, ref, new, cfg):
                    error = f"verification probe failed on replica {i}"
                    break
                self._flipped.append(self._key(handle))
                reg.set_phase(dep, phases.FLIPPING, flipped=i + 1)
                lat = self._lat_ewma(info["base"])
                if baseline_lat > 0.0 and \
                        lat > cfg.rollout_slo_factor * baseline_lat:
                    error = (f"SLO trip: latency EWMA {lat:.1f}ms > "
                             f"{cfg.rollout_slo_factor}x baseline "
                             f"{baseline_lat:.1f}ms")
                    break
        except Exception as e:  # noqa: BLE001 — journal, then roll back
            error = f"{type(e).__name__}: {e}"

        if error:
            self._roll_back(ctl, dep, old, cfg)
            rec = reg.rollback(dep, error)
        else:
            rec = reg.seal(dep)
            ray_tpu.get(ctl.set_model_version.remote(new), timeout=30)
            _trim_retained(dep, rec["retained"])
        ray_tpu.get(ctl.set_rollout_active.remote(False), timeout=30)
        ro = rec["rollout"]
        return {
            "deployment": dep, "from": old, "to": new,
            "phase": ro["phase"], "flipped": ro["flipped"],
            "replicas": ro["replicas"], "error": ro["error"],
            "max_flip_downtime_s": round(self.max_flip_downtime_s, 4),
            "seconds": round(_clk.monotonic() - t0, 4),
        }

    def _hold_for_operator(self, dep: str) -> str:
        """Between flips: honor the pause/abort control flag the CLI
        writes through the head."""
        flag = self._control(dep)
        if flag == "pause":
            self._registry.set_phase(dep, phases.PAUSED)
            while flag == "pause":
                _clk.sleep(0.2)
                flag = self._control(dep)
            if flag != "abort":
                self._registry.set_phase(dep, phases.FLIPPING)
        return flag

    def _flip_one(self, ctl, handle, ref, version: str, cfg) -> bool:
        """One replica through the drain->reload->probe->commit cycle.
        Returns False only on probe failure; a replica that died is
        dropped and does not fail the rollout (the set just shrinks,
        exactly as under any other death)."""
        import ray_tpu
        from ..actor_api import ActorMethod
        key = self._key(handle)
        t_out = _clk.monotonic()
        if not ray_tpu.get(ctl.begin_flip.remote(key), timeout=30):
            return True         # already gone (death, downscale)
        deadline = _clk.monotonic() + cfg.rollout_flip_drain_timeout_s
        try:
            while _clk.monotonic() < deadline:
                active = ray_tpu.get(
                    ActorMethod(handle, "_active_count").remote(),
                    timeout=10)
                if active == 0:
                    break
                _clk.sleep(0.02)
            res = ray_tpu.get(
                ActorMethod(handle, "_reload").remote(ref, version),
                timeout=cfg.rollout_probe_timeout_s +
                cfg.rollout_flip_drain_timeout_s)
        except Exception:   # noqa: BLE001 — replica died mid-flip
            ray_tpu.get(ctl.cancel_flip.remote(key, True), timeout=30)
            return True
        ok = bool(res.get("ok"))
        if ok and self._probe is not None:
            try:
                ok = bool(self._probe(handle))
            except Exception:   # noqa: BLE001 — probe raised: failed
                ok = False
        if ok:
            ray_tpu.get(ctl.commit_flip.remote(key, version),
                        timeout=30)
            self.max_flip_downtime_s = max(
                self.max_flip_downtime_s, _clk.monotonic() - t_out)
        else:
            ray_tpu.get(ctl.cancel_flip.remote(key, False), timeout=30)
        return ok

    def _roll_back(self, ctl, dep: str, old: str, cfg) -> None:
        """Re-flip every already-flipped replica to the retained old
        version.  With no retained old artifact (the initial deploy
        never staged one) the re-flip only re-tags — user state is the
        deploy-time weights already."""
        import ray_tpu
        from ..actor_api import ActorMethod
        old_ref = _retained(dep, old)
        for key in reversed(self._flipped):
            try:
                if not ray_tpu.get(ctl.begin_flip.remote(key),
                                   timeout=30):
                    continue
                handles = ray_tpu.get(ctl.flipping_handles.remote(),
                                      timeout=30)
                handle = next((h for h in handles
                               if self._key(h) == key), None)
                if handle is None:
                    continue
                deadline = _clk.monotonic() + \
                    cfg.rollout_flip_drain_timeout_s
                while _clk.monotonic() < deadline:
                    if ray_tpu.get(
                            ActorMethod(handle,
                                        "_active_count").remote(),
                            timeout=10) == 0:
                        break
                    _clk.sleep(0.02)
                ray_tpu.get(ActorMethod(handle, "_reload").remote(
                    old_ref, old),
                    timeout=cfg.rollout_probe_timeout_s +
                    cfg.rollout_flip_drain_timeout_s)
                ray_tpu.get(ctl.commit_flip.remote(key, old),
                            timeout=30)
            except Exception:   # noqa: BLE001 — replica died: drop it
                try:
                    ray_tpu.get(ctl.cancel_flip.remote(key, True),
                                timeout=30)
                except Exception:   # noqa: BLE001
                    pass


# -- module-level convenience (the public serve-adjacent API) ----------------

def rollout(artifact: bytes, app_name: str = "default",
            artifact_label: str = "", probe=None) -> dict:
    """Roll ``artifact`` across the running app; blocks until SEALED
    or ROLLED_BACK and returns the summary."""
    return RolloutController(artifact, app_name=app_name,
                             artifact_label=artifact_label,
                             probe=probe).run()


def rollout_status(deployment: str | None = None) -> dict:
    reg = VersionRegistry()
    if deployment is not None:
        rec = reg.record(deployment)
        return rec if rec is not None else {}
    return reg.all()


def pause_rollout(deployment: str) -> None:
    VersionRegistry().set_control(deployment, "pause")


def resume_rollout(deployment: str) -> None:
    VersionRegistry().set_control(deployment, "")


def abort_rollout(deployment: str) -> None:
    VersionRegistry().set_control(deployment, "abort")
