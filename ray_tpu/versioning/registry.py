"""Head-owned version registry, journaled into the GCS-snapshotted KV.

One JSON record per deployment under the ``version`` KV namespace
(``ver-<deployment>``).  Every mutation is a read-modify-write through
the internal KV — which lives head-side and rides the head's periodic
GCS snapshot — so the version table survives head restarts and standby
promotion without any machinery of its own: promotion restores the
same KV.  The registry is deliberately stateless (no in-memory cache):
a promoted head, a CLI process and the driver all read the same
journal.

A second key per deployment (``ctl-<deployment>``) carries the
operator control flag (``pause``/``abort``) the live
:class:`~ray_tpu.versioning.rollout.RolloutController` polls between
flips — the channel ``ray_tpu rollout --pause/--resume/--abort``
writes through the head RPC.
"""

from __future__ import annotations

import json

from ..common import clock as _clk
from ..common.config import get_config
from . import phases

_NS = "version"
_VER_PREFIX = "ver-"
_CTL_PREFIX = "ctl-"


def _kv():
    from ..experimental import internal_kv
    return internal_kv


class VersionRegistry:
    """CRUD + state-machine guard over the per-deployment journal."""

    # -- raw journal access --------------------------------------------------
    def record(self, deployment: str) -> dict | None:
        raw = _kv()._internal_kv_get(_VER_PREFIX + deployment,
                                     namespace=_NS)
        if not raw:
            return None
        return json.loads(raw.decode())

    def _save(self, deployment: str, rec: dict) -> None:
        _kv()._internal_kv_put(
            _VER_PREFIX + deployment,
            json.dumps(rec, sort_keys=True).encode(), namespace=_NS)

    def all(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for key in _kv()._internal_kv_list(_VER_PREFIX.encode(),
                                           namespace=_NS):
            name = key.decode()[len(_VER_PREFIX):]
            rec = self.record(name)
            if rec is not None:
                out[name] = rec
        return out

    # -- lifecycle -----------------------------------------------------------
    def ensure(self, deployment: str,
               artifact: str = "initial") -> dict:
        """Idempotently registers a deployment at ``v1``."""
        rec = self.record(deployment)
        if rec is not None:
            return rec
        rec = {
            "deployment": deployment,
            "current": "v1",
            "previous": None,
            "seq": 1,
            "artifacts": {"v1": artifact},
            "retained": ["v1"],
            "history": [{"version": "v1", "artifact": artifact,
                         "t": _clk.now()}],
            "rollout": None,
        }
        self._save(deployment, rec)
        return rec

    def stage(self, deployment: str, artifact: str) -> dict:
        """Allocate the next version and journal a STAGING rollout.
        Refuses while another rollout is active: version waves may run
        concurrently across deployments, never within one."""
        rec = self.ensure(deployment)
        ro = rec.get("rollout")
        if ro is not None and ro["phase"] not in phases.TERMINAL:
            raise RuntimeError(
                f"rollout {ro['id']} for {deployment!r} still "
                f"{ro['phase']}; one rollout per deployment at a time")
        rec["seq"] += 1
        new = f"v{rec['seq']}"
        now = _clk.now()
        rec["artifacts"][new] = artifact
        rec["rollout"] = {
            "id": f"{deployment}:{new}",
            "from": rec["current"],
            "to": new,
            "artifact": artifact,
            "phase": phases.STAGING,
            "flipped": 0,
            "replicas": 0,
            "t_start": now,
            "t_phase": now,
            "error": "",
            "transitions": [[phases.STAGING, now]],
        }
        # the old version's artifact stays retained until seal — the
        # rollback path re-flips onto it
        if rec["current"] not in rec["retained"]:
            rec["retained"].append(rec["current"])
        self._save(deployment, rec)
        self.set_control(deployment, "")    # clear stale pause/abort
        return rec

    def set_phase(self, deployment: str, phase: str, **fields) -> dict:
        rec = self.record(deployment)
        if rec is None or rec.get("rollout") is None:
            raise RuntimeError(f"no rollout journaled for {deployment!r}")
        ro = rec["rollout"]
        if phase != ro["phase"]:
            if phase not in phases.NEXT.get(ro["phase"], ()):
                raise RuntimeError(
                    f"illegal rollout transition {ro['phase']} -> "
                    f"{phase} for {deployment!r}")
            ro["phase"] = phase
            ro["t_phase"] = _clk.now()
            ro["transitions"].append([phase, ro["t_phase"]])
        ro.update(fields)
        self._save(deployment, rec)
        return rec

    def seal(self, deployment: str) -> dict:
        """Flip the table: the rollout's target becomes current, and
        retained artifacts trim to ``version_retain_count`` (the sealed
        old version drops out once past the retention window)."""
        rec = self.set_phase(deployment, phases.SEALED)
        ro = rec["rollout"]
        rec["previous"] = rec["current"]
        rec["current"] = ro["to"]
        rec["history"].append({"version": ro["to"],
                               "artifact": ro["artifact"],
                               "t": _clk.now()})
        keep = max(int(get_config().version_retain_count), 1)
        retained = [v for v in rec["retained"] if v != ro["to"]]
        retained.append(ro["to"])
        rec["retained"] = retained[-keep:]
        self._save(deployment, rec)
        return rec

    def rollback(self, deployment: str, error: str) -> dict:
        """Journal the failure; ``current`` never moved, so the old
        version simply stays authoritative."""
        return self.set_phase(deployment, phases.ROLLED_BACK,
                              error=error)

    def current(self, deployment: str) -> str:
        rec = self.record(deployment)
        return rec["current"] if rec else "v1"

    # -- operator control channel -------------------------------------------
    def control(self, deployment: str) -> str:
        raw = _kv()._internal_kv_get(_CTL_PREFIX + deployment,
                                     namespace=_NS)
        return raw.decode() if raw else ""

    def set_control(self, deployment: str, flag: str) -> None:
        if flag not in ("", "pause", "abort"):
            raise ValueError(f"unknown rollout control flag {flag!r}")
        _kv()._internal_kv_put(_CTL_PREFIX + deployment, flag.encode(),
                               namespace=_NS)
