"""Model-version plane: zero-downtime rolling weight hot-swap.

A deployment's weights are versioned (``v1``, ``v2``, ...) in a
:class:`VersionRegistry` journaled into the GCS-snapshotted KV — the
version table survives head restarts AND standby promotion for free,
because promotion restores the same KV snapshot.  A
:class:`RolloutController` rolls a new version across a live replica
set with zero accepted-request loss:

    STAGING -> BROADCASTING -> FLIPPING -> SEALED | ROLLED_BACK

The new artifact streams 1->N down the bandwidth-derated broadcast
tree (``broadcast/manager.py``) while routers keep serving the old
version; replicas then flip atomically one-at-a-time — each flip pulls
the replica out of routing, drains its in-flight requests behind the
``max_ongoing_requests`` cap, reloads, probes, and re-enters.  Session
-sticky rendezvous routing pins live sessions to a consistent version
until they end.  A failed rollout (replica death mid-broadcast,
verification-probe failure, or an SLO-regression trip on the
per-deployment p99 EWMA) rolls back by re-flipping already-flipped
replicas to the retained old version.

The simulator twin (``sim/rollout.py``) models the same state machine
on the virtual clock; the ``serve_rolling_update`` campaign drives it
under chaos with three dedicated invariants (mixed-version sessions,
rollout termination, old-version retention).
"""

from .phases import (BROADCASTING, FLIPPING, ROLLED_BACK, SEALED,
                     STAGING, TERMINAL)
from .registry import VersionRegistry
from .rollout import (RolloutController, abort_rollout, pause_rollout,
                      resume_rollout, rollout, rollout_status)

__all__ = [
    "STAGING", "BROADCASTING", "FLIPPING", "SEALED", "ROLLED_BACK",
    "TERMINAL", "VersionRegistry", "RolloutController", "rollout",
    "rollout_status", "pause_rollout", "resume_rollout",
    "abort_rollout",
]
