"""Runtime lock-order recorder — the dynamic half of rtlint's W2.

Static analysis (``tools/rtlint`` rule W2) infers the acquires-while-
holding digraph lexically; it cannot see cross-object nesting (object A
holding its lock while calling into object B which takes its own).
This module records the REAL acquisition order: ``install()`` replaces
``threading.Lock``/``threading.RLock`` with factories returning
instrumented wrappers that maintain a per-thread held-stack and add an
edge ``H -> L`` for every lock H held at the moment L is acquired.

Lock identity is the ALLOCATION SITE (``file:line`` of the constructor
call), so all instances created by one class's ``__init__`` collapse
into one graph node — the same granularity rtlint's static ids have.
Same-site self-edges (two instances of the same class nested) are
recorded under ``self_edges()`` but excluded from the cycle check:
statically indistinguishable, and commonly an ordered-by-address or
ordered-by-role pattern.

Gated by the ``rtlint_runtime_lock_order`` config knob (or the
``RT_RTLINT_RUNTIME_LOCK_ORDER`` env var before ``Config`` init, like
any knob): the chaos/drain suites run with it enabled and assert
``find_cycle() is None`` after every test — static analysis proposes,
the chaos plane disposes.

Overhead when installed is one thread-local list append per acquire and
a set-add per NEW edge; when not installed, zero (the stdlib factories
are untouched).
"""

from __future__ import annotations

import threading
import traceback

_real_lock = threading.Lock
_real_rlock = threading.RLock

_installed = False
_state_lock = _real_lock()
# edge -> (src_site, dst_site) observed count; witness kept for the first
_edges: dict[tuple[str, str], int] = {}
_witness: dict[tuple[str, str], str] = {}
_self_edges: dict[str, int] = {}
_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _alloc_site() -> str:
    """file:line of the code that called threading.Lock()/RLock():
    the innermost stack frame outside this module."""
    stack = traceback.extract_stack()
    for fr in reversed(stack):
        if fr.filename != __file__:
            fn = fr.filename
            # keep paths readable: trim to the package-relative tail
            for marker in ("ray_tpu/", "site-packages/", "lib/python"):
                i = fn.rfind(marker)
                if i >= 0:
                    fn = fn[i:]
                    break
            return f"{fn}:{fr.lineno}"
    return "<unknown>"


def _record_acquire(site: str) -> None:
    held = _held()
    if held:
        new_edges = []
        for h in held:
            if h == site:
                with _state_lock:
                    _self_edges[site] = _self_edges.get(site, 0) + 1
                continue
            new_edges.append((h, site))
        if new_edges:
            with _state_lock:
                for e in new_edges:
                    if e not in _edges:
                        _edges[e] = 0
                        _witness[e] = _thread_tag()
                    _edges[e] += 1
    held.append(site)


def _record_release(site: str) -> None:
    held = _held()
    # locks can release out of LIFO order; remove the most recent match
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


def _thread_tag() -> str:
    t = threading.current_thread()
    return t.name


class _TrackedLock:
    """Wraps a real (R)Lock; records acquisition-order edges.

    Implements the full lock protocol plus the private hooks
    ``threading.Condition`` uses (``_release_save`` etc.) so a tracked
    lock can back a Condition without the bookkeeping going stale.
    """

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site):
        self._inner = inner
        self._site = site

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquire(self._site)
        return got

    def release(self):
        self._inner.release()
        _record_release(self._site)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition integration (cpython threading.Condition duck-typing) --
    def _release_save(self):
        # Condition.wait: fully release (even reentrant holds)
        state = getattr(self._inner, "_release_save", None)
        _record_release(self._site)
        if state is not None:
            return state()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        _record_acquire(self._site)

    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        # plain Lock heuristic (what Condition itself does)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<TrackedLock {self._site} of {self._inner!r}>"


class _TrackedRLock(_TrackedLock):
    """Reentrant variant: re-acquisition by the owner records no edge
    (it cannot deadlock against anything new)."""

    __slots__ = ("_count",)

    def __init__(self, inner, site):
        super().__init__(inner, site)
        self._count = threading.local()

    def _depth(self):
        return getattr(self._count, "n", 0)

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._depth() == 0:
                _record_acquire(self._site)
            self._count.n = self._depth() + 1
        return got

    def release(self):
        self._inner.release()
        self._count.n = max(0, self._depth() - 1)
        if self._depth() == 0:
            _record_release(self._site)

    def _release_save(self):
        state = self._inner._release_save()
        _record_release(self._site)
        n, self._count.n = self._depth(), 0
        return (state, n)

    def _acquire_restore(self, state):
        inner_state, n = state
        self._inner._acquire_restore(inner_state)
        self._count.n = n
        _record_acquire(self._site)

    def _is_owned(self):
        return self._inner._is_owned()


def _lock_factory():
    return _TrackedLock(_real_lock(), _alloc_site())


def _rlock_factory():
    return _TrackedRLock(_real_rlock(), _alloc_site())


# -- public API --------------------------------------------------------------

def install() -> None:
    """Start tracking: locks constructed AFTER this call are recorded.
    Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    """Restore the stdlib factories (existing tracked locks keep
    working — they only stop being created)."""
    global _installed
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Drop the recorded graph (not the installation)."""
    with _state_lock:
        _edges.clear()
        _witness.clear()
        _self_edges.clear()


def edges() -> dict[tuple[str, str], int]:
    with _state_lock:
        return dict(_edges)


def self_edges() -> dict[str, int]:
    with _state_lock:
        return dict(_self_edges)


def graph() -> dict[str, set[str]]:
    adj: dict[str, set[str]] = {}
    for (a, b) in edges():
        adj.setdefault(a, set()).add(b)
    return adj


def find_cycle() -> list[str] | None:
    """First lock-order cycle in the observed graph, or None."""
    adj = graph()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    stack: list[str] = []
    out: list[list[str]] = []

    def dfs(n):
        color[n] = GRAY
        stack.append(n)
        for m in sorted(adj.get(n, ())):
            if out:
                break
            c = color.get(m, WHITE)
            if c == WHITE:
                dfs(m)
            elif c == GRAY:
                out.append(stack[stack.index(m):] + [m])
        stack.pop()
        color[n] = BLACK

    for n in sorted(adj):
        if not out and color[n] == WHITE:
            dfs(n)
    return out[0] if out else None


def assert_acyclic() -> None:
    cyc = find_cycle()
    if cyc is not None:
        w = [f"{a} -> {b} (first seen on thread {_witness.get((a, b), '?')})"
             for a, b in zip(cyc, cyc[1:])]
        raise AssertionError(
            "runtime lock-order cycle observed:\n  " + "\n  ".join(w))


def maybe_install_from_config() -> bool:
    """Install iff the ``rtlint_runtime_lock_order`` knob is on.
    Returns whether tracking is installed after the call."""
    from .config import get_config
    if getattr(get_config(), "rtlint_runtime_lock_order", False):
        install()
    return _installed
