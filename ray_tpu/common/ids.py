"""Unique identifiers for every entity in the system.

Reference parity: upstream Ray defines 128-bit (and longer, structured) binary
ids in ``src/ray/common/id.h`` — ``ObjectID``/``TaskID``/``ActorID``/``JobID``/
``NodeID``/``PlacementGroupID`` — with structured derivation (an ObjectID embeds
the TaskID of its producing task plus a put/return index, a TaskID embeds the
ActorID/JobID, ...).  [Reference mount was empty; path cited per SURVEY.md §1
layer 1, unverified line numbers.]

TPU-first design notes: ids never reach the device — device-side scheduling
works on dense *indices* (node row numbers, group row numbers).  Ids exist only
on the host control plane, so a compact ``bytes``-backed value type is all we
need.  Structured derivation is kept because lineage reconstruction (SURVEY
§5.3) and ownership accounting need to map an ObjectID back to its producing
TaskID without a lookup table.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import ClassVar

_NIL = b"\xff"

# fast unique-bytes stream: one getrandom(2) syscall per TaskID
# (~30 us each) dominates the tiny-task submit path, so hot-path ids
# draw from an os.urandom-seeded PRNG instead — full 64-bit entropy
# per draw (collision odds identical to true-random bytes), reseeded
# on fork/spawn (pid check) so child processes never share a stream
_fast_rng = None
_fast_rng_pid = -1
_fast_rng_lock = threading.Lock()


def fast_random_bytes(n: int) -> bytes:
    global _fast_rng, _fast_rng_pid
    rng = _fast_rng
    if rng is None or _fast_rng_pid != os.getpid():
        import random
        with _fast_rng_lock:
            if _fast_rng is None or _fast_rng_pid != os.getpid():
                _fast_rng = random.Random(os.urandom(32))
                _fast_rng_pid = os.getpid()
            rng = _fast_rng
    # randbytes is a single C call: atomic under the GIL, so concurrent
    # threads get distinct (never interleaved/corrupted) draws
    return rng.randbytes(n)


class BaseID:
    """Immutable binary id. Subclasses fix SIZE (bytes)."""

    SIZE: ClassVar[int] = 16
    __slots__ = ("_bin",)

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {binary!r}"
            )
        object.__setattr__(self, "_bin", binary)

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    # -- accessors ----------------------------------------------------------
    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def is_nil(self) -> bool:
        return self._bin == _NIL * self.SIZE

    # -- dunder -------------------------------------------------------------
    def __setattr__(self, *_):
        raise AttributeError(f"{type(self).__name__} is immutable")

    def __reduce__(self):
        # default slots-state pickling would setattr on load, which the
        # immutability guard forbids; rebuild through __init__ instead
        return (type(self), (self._bin,))

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __hash__(self):
        return hash((type(self).__name__, self._bin))

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]}…)" if self.SIZE > 8 \
            else f"{type(self).__name__}({self.hex()})"

    def __lt__(self, other):
        return self._bin < other._bin


class JobID(BaseID):
    SIZE = 4

    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(4, "big"))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 unique bytes + 4-byte JobID suffix."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(12) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[12:])

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        return cls(_NIL * 12 + job_id.binary())


class PlacementGroupID(BaseID):
    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(os.urandom(12) + job_id.binary())


class TaskID(BaseID):
    """8 unique bytes + 16-byte parent ActorID (which embeds the JobID)."""

    SIZE = 24

    @classmethod
    def for_task(cls, job_id: JobID, actor_id: ActorID | None = None) -> "TaskID":
        actor = actor_id if actor_id is not None else ActorID.nil_for_job(job_id)
        return cls(fast_random_bytes(8) + actor.binary())

    @classmethod
    def deterministic(cls, seed: bytes, actor_id: ActorID) -> "TaskID":
        return cls(hashlib.sha256(seed).digest()[:8] + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bin[8:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    """24-byte producing TaskID + 4-byte index (big endian).

    index semantics mirror the reference: return values of a task get indices
    1..n; ``put`` objects use a separate per-worker counter offset by 2**31 so
    the two namespaces never collide.
    """

    SIZE = 28
    PUT_INDEX_OFFSET = 1 << 31

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        idx = cls.PUT_INDEX_OFFSET + put_index
        return cls(task_id.binary() + idx.to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bin[:24])

    def index(self) -> int:
        return int.from_bytes(self._bin[24:], "big")

    def is_put(self) -> bool:
        return self.index() >= self.PUT_INDEX_OFFSET


ObjectRefID = ObjectID  # alias used by the runtime layer
