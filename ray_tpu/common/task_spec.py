"""Task specifications — the unit of work the scheduler places.

Reference parity: upstream Ray's ``TaskSpecification``
(``src/ray/common/task/task_spec.h``, wire form ``TaskSpec`` in
``src/ray/protobuf/common.proto``) carries function descriptor, args (inline
or ObjectRef), resource demands, scheduling strategy, retry policy, and — the
field the scheduler keys on — a *scheduling class* interning the (resource
request, strategy, function) triple so equal tasks share lease pools.
[Cited per SURVEY.md §1/§3.2; reference mount empty, line numbers unavailable.]

TPU-first: the scheduling class is load-bearing here — the device kernel
batches pending tasks *by scheduling class* (identical demand vectors are
water-fill-able as one group, see ray_tpu/ops/hybrid_kernel.py), so the class
key is computed eagerly at spec construction.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any

from .ids import ActorID, JobID, NodeID, PlacementGroupID, TaskID
from .resources import ResourceRequest


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2
    DRIVER_TASK = 3


class SchedulingStrategyKind(enum.Enum):
    DEFAULT = 0            # hybrid policy
    SPREAD = 1             # round-robin over feasible nodes
    NODE_AFFINITY = 2      # pin to node (soft or hard)
    PLACEMENT_GROUP = 3    # pin to a reserved bundle
    NODE_LABEL = 4         # restrict to nodes matching a label selector


@dataclass(frozen=True)
class SchedulingStrategy:
    kind: SchedulingStrategyKind = SchedulingStrategyKind.DEFAULT
    # NODE_AFFINITY
    node_id: NodeID | None = None
    soft: bool = False
    # PLACEMENT_GROUP
    placement_group_id: PlacementGroupID | None = None
    bundle_index: int = -1
    # NODE_LABEL: sorted ((key, value), ...) pairs (tuple: frozen+hashable)
    label_selector: tuple = ()

    def key(self) -> tuple:
        return (self.kind.value,
                self.node_id.binary() if self.node_id else b"",
                self.soft,
                self.placement_group_id.binary()
                if self.placement_group_id else b"",
                self.bundle_index,
                self.label_selector)


DEFAULT_STRATEGY = SchedulingStrategy()


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    function_descriptor: str          # module:qualname for normal tasks
    args: tuple = ()                  # mixed inline values / ObjectRefs
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    resources: ResourceRequest = field(default_factory=ResourceRequest)
    strategy: SchedulingStrategy = DEFAULT_STRATEGY
    max_retries: int = 0
    actor_id: ActorID | None = None   # set for actor creation/actor tasks
    # per-task runtime environment (env_vars/working_dir/py_modules/pip)
    runtime_env: dict | None = None
    # tracing: (trace_id, parent_span_id) propagated caller -> task
    # when ``tracing_enabled`` (reference: OpenTelemetry context in
    # task specs behind RAY_TRACING_ENABLED)
    trace_ctx: tuple | None = None
    # lineage: object deps this spec needs (resolved by DependencyManager)
    dependencies: tuple = ()
    # retry bookkeeping (mutated by TaskManager)
    attempt_number: int = 0
    # worker recycling (reference @ray.remote(max_calls=N)): the
    # executing worker retires after this many invocations of the
    # function — the pressure valve for tasks that leak native memory
    max_calls: int = 0

    def scheduling_class(self) -> tuple:
        """Interned identity for batch grouping — equal classes are
        order-equivalent inside one scheduling round."""
        return (self.resources.key(), self.strategy.key())

    def is_actor_task(self) -> bool:
        return self.task_type in (TaskType.ACTOR_TASK,
                                  TaskType.ACTOR_CREATION_TASK)
