"""Resource quantities, requests, and per-node resource state.

Reference parity: upstream Ray models resource quantities as ``FixedPoint``
(integer, 1e-4 granularity) inside ``ResourceSet``/``ResourceRequest``/
``NodeResources`` (``src/ray/common/scheduling/fixed_point.h``,
``resource_request.h``, ``cluster_resource_data.h``).  [Cited per SURVEY.md §1
layer 1 / §2.1; reference mount empty, line numbers unavailable.]

TPU-first contract
------------------
Quantities are **int32 centi-units** (``cu`` = value x 100, granularity 0.01).
The granularity is coarser than the reference's 1e-4 by design: it bounds the
integer magnitudes so that the scheduling score

    score_fp = ((used + req) * SCALE) // total        (SCALE = 2**12)

can be computed **exactly in int32 on the device** (no int64, which TPUs lack
without jax_enable_x64; no float division, which XLA does not guarantee to be
bit-identical across platforms).  With per-node per-resource totals capped at
``MAX_TOTAL_CU = 2**17`` cu (= 1310.72 units) the intermediate
``(used + req) * SCALE <= 2*2**17*2**12 = 2**30`` never overflows int32.  The
CPU oracle uses the identical integer formulas, which is what makes
bit-for-bit parity a property instead of a hope (SURVEY §7 hard part 5).

Memory-like resources are therefore expressed in GiB (so "memory": 128 means
128 GiB, well under the cap), not bytes as in the reference.
"""

from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

# --- fixed-point quantity contract -----------------------------------------
CU_PER_UNIT = 100                  # centi-units per resource unit
MAX_TOTAL_CU = 1 << 17             # per-node, per-resource cap (int32 safety)

# Predefined resource names get the first dense columns, in this order, so
# that column indices are stable across hosts without coordination.
PREDEFINED_RESOURCES = ("CPU", "GPU", "TPU", "memory", "object_store_memory")

# Resources whose *unit* is implicit GiB in user-facing dicts.
_GIB_RESOURCES = frozenset({"memory", "object_store_memory"})


def to_cu(value: float | int) -> int:
    """Quantize a user-facing quantity to centi-units (round half up)."""
    if value < 0:
        raise ValueError(f"negative resource quantity: {value}")
    cu = int(float(value) * CU_PER_UNIT + 0.5)  # round half up, not banker's
    if cu > MAX_TOTAL_CU:
        raise ValueError(
            f"resource quantity {value} exceeds cap "
            f"{MAX_TOTAL_CU / CU_PER_UNIT} units (int32 score-arithmetic "
            f"contract, see module docstring)")
    return cu


def from_cu(cu: int) -> float:
    return cu / CU_PER_UNIT


class ResourceIndex:
    """Stable mapping resource-name <-> dense column index.

    The device kernels operate on dense ``(nodes, R)`` arrays; this registry
    assigns each resource name (predefined first, then custom in first-seen
    order) a column.  Mirrors the reference's ``ResourceID`` interning
    (``src/ray/common/scheduling/scheduling_ids.h``) [SURVEY §2.1, unverified].
    """

    def __init__(self, extra: Iterable[str] = ()):
        self._names: list[str] = list(PREDEFINED_RESOURCES)
        self._index: dict[str, int] = {n: i for i, n in enumerate(self._names)}
        for name in extra:
            self.get_or_add(name)

    def get_or_add(self, name: str) -> int:
        idx = self._index.get(name)
        if idx is None:
            idx = len(self._names)
            self._names.append(name)
            self._index[name] = idx
        return idx

    def get(self, name: str) -> int | None:
        return self._index.get(name)

    def name(self, idx: int) -> str:
        return self._names[idx]

    @property
    def num_resources(self) -> int:
        return len(self._names)

    def names(self) -> tuple[str, ...]:
        return tuple(self._names)


class ResourceRequest:
    """An immutable demand vector (what a task/actor/bundle asks for).

    Reference: ``src/ray/common/scheduling/resource_request.h`` [SURVEY §2.1].
    """

    __slots__ = ("_cu", "_key")

    def __init__(self, resources: Mapping[str, float] | None = None):
        cu: dict[str, int] = {}
        for name, value in (resources or {}).items():
            q = to_cu(value)
            if q:
                cu[name] = q
        self._cu = cu
        self._key = tuple(sorted(cu.items()))

    @classmethod
    def from_cu_dict(cls, cu: Mapping[str, int]) -> "ResourceRequest":
        req = cls.__new__(cls)
        req._cu = {k: int(v) for k, v in cu.items() if v}
        req._key = tuple(sorted(req._cu.items()))
        return req

    def cu(self) -> Mapping[str, int]:
        return dict(self._cu)

    def is_empty(self) -> bool:
        return not self._cu

    def to_dict(self) -> dict[str, float]:
        return {k: from_cu(v) for k, v in self._cu.items()}

    def dense(self, index: ResourceIndex, width: int | None = None) -> np.ndarray:
        """Dense int32 cu vector under ``index`` (interning unseen names)."""
        cols = {index.get_or_add(name): q for name, q in self._cu.items()}
        w = width if width is not None else index.num_resources
        vec = np.zeros(w, dtype=np.int32)
        for col, q in cols.items():
            vec[col] = q
        return vec

    # scheduling-class identity: tasks with equal keys are batch-groupable
    def key(self) -> tuple:
        return self._key

    def __eq__(self, other):
        return isinstance(other, ResourceRequest) and other._key == self._key

    def __hash__(self):
        return hash(self._key)

    def __repr__(self):
        return f"ResourceRequest({self.to_dict()})"


class NodeResources:
    """Total + available capacity and labels for one node.

    Reference: ``NodeResources`` in
    ``src/ray/common/scheduling/cluster_resource_data.h`` [SURVEY §2.1].
    """

    __slots__ = ("total_cu", "available_cu", "labels")

    def __init__(self, total: Mapping[str, float],
                 labels: Mapping[str, str] | None = None):
        self.total_cu: dict[str, int] = {
            k: to_cu(v) for k, v in total.items() if to_cu(v)}
        self.available_cu: dict[str, int] = dict(self.total_cu)
        self.labels: dict[str, str] = dict(labels or {})

    # -- queries ------------------------------------------------------------
    def is_feasible(self, req: ResourceRequest) -> bool:
        return all(self.total_cu.get(k, 0) >= q for k, q in req.cu().items())

    def is_available(self, req: ResourceRequest) -> bool:
        return all(self.available_cu.get(k, 0) >= q
                   for k, q in req.cu().items())

    # -- mutation (local resource manager) ----------------------------------
    def allocate(self, req: ResourceRequest) -> bool:
        if not self.is_available(req):
            return False
        for k, q in req.cu().items():
            self.available_cu[k] -= q
        return True

    def free(self, req: ResourceRequest) -> None:
        for k, q in req.cu().items():
            self.available_cu[k] = min(
                self.total_cu.get(k, 0), self.available_cu.get(k, 0) + q)

    def to_dict(self) -> dict:
        return {
            "total": {k: from_cu(v) for k, v in self.total_cu.items()},
            "available": {k: from_cu(v) for k, v in self.available_cu.items()},
            "labels": dict(self.labels),
        }

    def __repr__(self):
        return f"NodeResources({self.to_dict()})"
