"""Status / error taxonomy for the runtime.

Reference parity: upstream Ray's ``ray::Status`` (``src/ray/common/status.h``)
plus the user-visible exception hierarchy in ``python/ray/exceptions.py``
(``RayTaskError``, ``RayActorError``, ``ObjectLostError``,
``GetTimeoutError``, ...).  [SURVEY.md §1; reference mount empty.]
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base for all framework errors."""


class TaskError(RayTpuError):
    """A task raised; re-raised at ray_tpu.get() with the remote traceback."""

    def __init__(self, function_descriptor: str, cause_repr: str,
                 traceback_str: str = ""):
        self.function_descriptor = function_descriptor
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        super().__init__(
            f"task {function_descriptor} failed: {cause_repr}\n{traceback_str}")


class ActorError(RayTpuError):
    """The actor died before or during this method call."""


class ActorUnavailableError(ActorError):
    pass


class ObjectLostError(RayTpuError):
    """Object is gone and lineage reconstruction was impossible/exhausted."""


class ObjectReconstructionError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class BackPressureError(RayTpuError):
    """A deployment's bounded request queue is full; the request was
    shed instead of queued (reference: ``serve.exceptions.BackPressureError``
    raised when ``max_queued_requests`` is exceeded)."""


class WorkerCrashedError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass


class InfeasibleError(RayTpuError):
    """No node in the cluster can ever satisfy the request."""


class RuntimeEnvSetupError(RayTpuError):
    pass
