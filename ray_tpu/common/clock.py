"""Clock seam: real vs. virtual (event-driven) time for the control plane.

Reference parity: upstream hardwires ``absl::Now()``/``std::chrono``
throughout the GCS and raylet, which is exactly why its fault-tolerance
logic can only be exercised against wall-clock test clusters.  Routing
every control-plane timestamp, timeout and sleep through one seam is
what lets the in-process simulator (``ray_tpu/sim/``) run the same
state machines under a virtual clock: 10k nodes' worth of heartbeats,
lease deadlines and breaker cooldowns advance event-by-event with no
sockets and no wall-clock sleeps, deterministically.

Two implementations:

- ``RealClock`` — delegates to ``time.time/monotonic/sleep``; installed
  by default, so production behavior is byte-identical to calling the
  ``time`` module directly.
- ``VirtualClock`` — a discrete-event scheduler.  ``monotonic()`` is a
  number the owner advances; ``sleep(s)`` moves virtual time forward and
  fires due timers in deterministic ``(time, seq)`` order.  Strictly
  single-threaded by design: determinism is the point, and the simulator
  is the only intended owner.

Call sites in ``ray_tpu/runtime/`` and ``ray_tpu/rpc/`` use the
module-level helpers (``now()``, ``monotonic()``, ``sleep()``) so the
seam is one import and zero indirection to read.  rtlint rule W5 flags
control-plane code that bypasses it.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time as _time

__all__ = ["Clock", "RealClock", "VirtualClock", "get_clock", "install",
           "uninstall", "installed_virtual", "now", "monotonic", "sleep"]


class Clock:
    """The seam.  ``time()`` is wall-ish epoch time (timestamps in logs
    and persisted records), ``monotonic()`` is for deadlines/intervals,
    ``sleep()`` blocks (really or virtually)."""

    def time(self) -> float:
        raise NotImplementedError

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class RealClock(Clock):
    """Production clock: the ``time`` module, untouched."""

    time = staticmethod(_time.time)
    monotonic = staticmethod(_time.monotonic)
    sleep = staticmethod(_time.sleep)


class VirtualClock(Clock):
    """Deterministic discrete-event clock for the simulator.

    Events are ``(fire_time, seq, callback)`` on a heap; ``seq`` breaks
    time ties in schedule order, so replays are bit-for-bit.  Callbacks
    may schedule further events and may call ``sleep()`` (which recurses
    into ``advance``); time only moves forward.
    """

    def __init__(self, start: float = 0.0, epoch: float = 1.7e9):
        self._now = float(start)
        self._epoch = float(epoch)          # time() = epoch + monotonic
        self._heap: list = []               # (t, seq, callback or None)
        self._seq = itertools.count()
        self.fired = 0                      # events dispatched (stats)

    # -- Clock interface -----------------------------------------------------
    def time(self) -> float:
        return self._epoch + self._now

    def monotonic(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        """Virtual sleep: advance time, firing timers that come due."""
        self.advance(max(0.0, float(seconds)))

    # -- event scheduling ----------------------------------------------------
    def call_later(self, delay: float, fn) -> list:
        """Schedule ``fn()`` at ``now + delay``.  Returns a cancellable
        handle (mutate ``handle[2] = None`` via :meth:`cancel`)."""
        entry = [self._now + max(0.0, float(delay)), next(self._seq), fn]
        heapq.heappush(self._heap, entry)
        return entry

    def cancel(self, handle: list) -> None:
        handle[2] = None        # tombstone; popped lazily

    def pending(self) -> int:
        return sum(1 for e in self._heap if e[2] is not None)

    def next_event_time(self) -> float | None:
        while self._heap and self._heap[0][2] is None:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None

    def advance(self, dt: float) -> int:
        """Move time forward by ``dt``, dispatching due events in
        deterministic order.  Returns the number fired."""
        return self.run_until(self._now + max(0.0, float(dt)))

    def run_until(self, t: float) -> int:
        """Dispatch every event scheduled at or before ``t``; leaves
        ``monotonic() == max(t, now)``."""
        fired = 0
        while self._heap and self._heap[0][0] <= t:
            when, _, fn = heapq.heappop(self._heap)
            if fn is None:
                continue
            if when > self._now:
                self._now = when
            fired += 1
            self.fired += 1
            fn()
        if t > self._now:
            self._now = t
        return fired

    def run_until_idle(self, max_time: float | None = None) -> int:
        """Drain the heap (up to ``max_time``), the quiesce primitive
        invariant checks rely on."""
        fired = 0
        while True:
            nxt = self.next_event_time()
            if nxt is None or (max_time is not None and nxt > max_time):
                return fired
            fired += self.run_until(nxt)


# -- process-global install (same shape as chaos._active) --------------------
_default = RealClock()
_active: Clock = _default


def get_clock() -> Clock:
    return _active


def install(clock: Clock) -> Clock:
    """Swap the process clock (the simulator installs a VirtualClock
    for the duration of a campaign).  Returns the installed clock."""
    global _active
    _active = clock
    return clock


def uninstall() -> None:
    global _active
    _active = _default


def installed_virtual() -> bool:
    return isinstance(_active, VirtualClock)


# -- the helpers control-plane code imports ----------------------------------
def now() -> float:
    """Epoch-ish timestamp (``time.time`` under the real clock)."""
    return _active.time()


def monotonic() -> float:
    return _active.monotonic()


def sleep(seconds: float) -> None:
    _active.sleep(seconds)
